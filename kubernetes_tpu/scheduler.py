"""Scheduler shell: owns the scheduling loop, one pod per cycle (or a burst
per launch), assume → bind pipeline, informer wiring, failure re-queue.

Mirrors pkg/scheduler/scheduler.go (New :121, Run :250, scheduleOne :438,
assume :382, bind :411, recordSchedulingFailure :266) and
pkg/scheduler/eventhandlers.go:319 AddAllEventHandlers. The algorithm is
pluggable: the oracle (pure Python, the parity referee) or the TPU kernel
path (core.TPUScheduler); binding I/O stays off the decision path like the
reference's bind goroutine (scheduler.go:523).
"""
from __future__ import annotations

import copy
import itertools
import threading
import time
import urllib.error
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu import chaos, obs
from kubernetes_tpu.api.types import (
    Pod, Node, PodCondition, POD_SCHEDULED, CONDITION_FALSE,
    REASON_UNSCHEDULABLE, REASON_SCHEDULER_ERROR,
)
from kubernetes_tpu.coscheduling.types import (
    PHASE_PRESCHEDULING, pod_group_key,
)
from kubernetes_tpu.store.record import EventRecorder, NORMAL, WARNING
from kubernetes_tpu.cache.cache import SchedulerCache, Snapshot
from kubernetes_tpu.core import StaleNodeRefusal
from kubernetes_tpu.oracle.gang import GangTrial
from kubernetes_tpu.oracle.generic_scheduler import (
    GenericScheduler, FitError, ScheduleResult, default_priority_configs,
)
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.store.store import (
    Store, PODS, NODES, PODGROUPS, SERVICES, REPLICASETS, PDBS, PVS, PVCS,
    ConflictError, FencedError, NotFoundError,
)
from kubernetes_tpu.oracle.volumes import VolumeListers, VolumeBinder
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.framework.v1alpha1 import (
    Framework, Registry, PluginContext, UNSCHEDULABLE as FW_UNSCHEDULABLE,
)
from kubernetes_tpu.utils.clock import Clock, RealClock
from kubernetes_tpu.utils.tracing import Trace, SLOW_CYCLE_THRESHOLD

DEFAULT_SCHEDULER_NAME = "default-scheduler"

#: per-process scheduler instance sequence: wave dedupe tokens must be
#: unique PER INSTANCE, not per scheduler name — an active-active fleet
#: runs several instances under one profile name against one store, and
#: name-keyed tokens would alias their waves in the dedupe map (instance
#: B's wave 1 answered with instance A's recorded result)
_INSTANCE_SEQ = itertools.count(1)

# gang (PodGroup) scheduling observability — the obs catalogue additions:
# attempts by outcome, and how long a gang waited from group creation (or
# first sighting) to its committed placement
GANG_ATTEMPTS = obs.counter(
    "gang_attempts_total",
    "Atomic PodGroup placement attempts, by outcome: scheduled (whole "
    "gang committed), rejected (a member found no node — everything "
    "rewound, group parked), incomplete (fewer than minMember members "
    "queued), degraded (plugins/volumes force the per-pod path), "
    "error (members vanished between trial and commit).", ("outcome",))
GANG_WAIT = obs.histogram(
    "gang_wait_duration_seconds",
    "Seconds from PodGroup creation (or first scheduler sighting) to the "
    "gang's committed placement.",
    buckets=(0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600))
STALE_BINDS = obs.counter(
    "stale_bind_requeues_total",
    "Bind decisions refused because the target node vanished between "
    "decision and commit (mid-burst node death): the pod is re-queued "
    "with backoff in creation order, and the dead node's device-mirror "
    "row, victim-table row, cache entry, and NodeTree slot are "
    "invalidated eagerly (the informer's DELETED event confirms later).")
CLUSTER_UTILIZATION = obs.gauge(
    "cluster_resource_utilization",
    "Cluster-wide requested/allocatable fill fraction by resource "
    "(cpu/memory/ephemeral_storage), computed from the scheduler's "
    "NodeInfo snapshot at collect time — the packing-lane report and "
    "the tuner reward's live input (round 22).", ("resource",))
COMMIT_RETRIES = obs.counter(
    "store_commit_retries_total",
    "commit_wave store-write retries by the scheduler's idempotent retry "
    "loop, by outcome: retried (another attempt followed), recovered (a "
    "retry landed — or deduped against a wave that had already landed "
    "under the same token), exhausted (all attempts failed; the per-pod "
    "crash-resolution path took over).", ("outcome",))

#: exception classes the commit retry loop treats as transient: the chaos
#: plane's injected store fault, transport-level failures (the remote
#: store), and server-side 5xx (classified by the remote client)
def _retryable_store_error(exc: BaseException) -> bool:
    if isinstance(exc, chaos.SchedulerCrash):
        return False                 # a crash stand-in is never "transient"
    if isinstance(exc, chaos.InjectedFault):
        return True
    if isinstance(exc, (urllib.error.URLError, OSError, TimeoutError)):
        return True
    code = getattr(exc, "code", None)
    return code in (500, 502, 503, 504)


class Histogram:
    """Prometheus-style cumulative histogram (reference buckets:
    ExponentialBuckets(0.001, 2, 15), metrics.go:93)."""

    BOUNDS = tuple(0.001 * 2 ** i for i in range(15))

    def __init__(self):
        self.buckets = [0] * len(self.BOUNDS)
        self.count = 0
        self.sum = 0.0

    def observe(self, seconds: float) -> None:
        self.observe_many(seconds, 1)

    def observe_many(self, seconds: float, count: int) -> None:
        """`count` identical observations in one pass — the burst commit
        records its per-pod share without 10k bucket walks."""
        if count <= 0:
            return
        self.count += count
        self.sum += seconds * count
        for i, b in enumerate(self.BOUNDS):
            if seconds <= b:
                self.buckets[i] += count

    def __eq__(self, other) -> bool:
        return (isinstance(other, Histogram)
                and self.buckets == other.buckets
                and self.count == other.count and self.sum == other.sum)

    def render(self, name: str, labels: str = "") -> list[str]:
        sep = "," if labels else ""
        out = []
        for i, b in enumerate(self.BOUNDS):
            out.append(f'{name}_bucket{{{labels}{sep}le="{b:g}"}} '
                       f'{self.buckets[i]}')
        out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {self.count}')
        out.append(f'{name}_sum{{{labels}}} {self.sum:.6f}'
                   if labels else f'{name}_sum {self.sum:.6f}')
        out.append(f'{name}_count{{{labels}}} {self.count}'
                   if labels else f'{name}_count {self.count}')
        return out


@dataclass
class SchedulerMetrics:
    """Counter mirror of pkg/scheduler/metrics/metrics.go."""
    schedule_attempts: dict[str, int] = field(default_factory=lambda: {
        "scheduled": 0, "unschedulable": 0, "error": 0})
    binding_count: int = 0
    preemption_attempts: int = 0
    preemption_victims: int = 0
    e2e_latency_sum: float = 0.0
    # per-phase duration histograms (scheduling_duration_seconds{operation},
    # metrics.go:67-169) — TPU-shaped phases: encode (host feature
    # encoding), kernel (device dispatch), fetch (device->host readback),
    # plus the reference's algorithm/preemption/binding/e2e
    phase_duration: dict[str, "Histogram"] = field(default_factory=dict)
    binding_duration: "Histogram" = field(default_factory=lambda: Histogram())
    e2e_duration: "Histogram" = field(default_factory=lambda: Histogram())

    def observe(self, result: str, count: int = 1) -> None:
        self.schedule_attempts[result] = \
            self.schedule_attempts.get(result, 0) + count

    def observe_phase(self, phase: str, seconds: float,
                      count: int = 1) -> None:
        h = self.phase_duration.get(phase)
        if h is None:
            h = self.phase_duration[phase] = Histogram()
        h.observe_many(seconds, count)

    def reset(self) -> None:
        """DELETE /metrics analog. Re-derives every field from the
        dataclass defaults, so a newly added field can never be silently
        missed the way the old hand-copied reset_metrics field list could
        (a fresh instance IS the definition of 'reset')."""
        import dataclasses
        fresh = type(self)()
        for f in dataclasses.fields(self):
            setattr(self, f.name, getattr(fresh, f.name))


class Scheduler:
    """One scheduler instance: queue + cache + algorithm + binder."""

    # slow-cycle trace threshold (generic_scheduler.go:186 uses 100ms): a
    # serial cycle slower than this logs its step timeline via utils.Trace
    slow_cycle_threshold = SLOW_CYCLE_THRESHOLD

    def __init__(self, store: Store,
                 scheduler_name: str = DEFAULT_SCHEDULER_NAME,
                 algorithm=None,
                 use_tpu: bool = False,
                 percentage_of_nodes_to_score: int = 50,
                 hard_pod_affinity_weight: int = 1,
                 clock: Optional[Clock] = None,
                 disable_preemption: bool = False,
                 plugin_registry: Optional[Registry] = None,
                 plugins_enabled: Optional[list] = None,
                 plugin_args: Optional[dict] = None,
                 predicate_names: Optional[list] = None,
                 priority_weights: Optional[dict] = None,
                 extenders: Optional[list] = None,
                 mesh=None,
                 profiles=None):
        self.store = store
        self.name = scheduler_name
        # scheduling profiles (round 19): a profiles.ProfileSet makes THIS
        # process serve every named profile — responsibility is membership
        # in the set (unknown schedulerNames are REPORTED, never
        # default-scored), per-pod scoring selects the profile's weight
        # row ([profiles x priorities] tensor on the TPU path, per-profile
        # PriorityConfig lists on the oracle path), and rank-aware
        # profiles turn on gang set-scoring. Mutually exclusive with the
        # single-vector priority_weights.
        if profiles is not None:
            if priority_weights is not None:
                raise ValueError(
                    "profiles and priority_weights are mutually exclusive")
            profiles.validate()
        self.profiles = profiles
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.recorder = EventRecorder(store, component=scheduler_name)
        self.clock = clock or RealClock()
        self.cache = SchedulerCache(clock=self.clock)
        self.queue = PriorityQueue(clock=self.clock)
        self.metrics = SchedulerMetrics()
        self.informers = InformerFactory(store)
        self.disable_preemption = disable_preemption
        self._snapshot = Snapshot()
        self._stop = threading.Event()
        self._bind_threads: list[threading.Thread] = []
        # idempotent commit retry: one fresh token per wave (REUSED across
        # that wave's retries) keys the store's dedupe map; the prefix is
        # instance-unique (see _INSTANCE_SEQ) so fleet peers sharing a
        # profile name can never dedupe-alias each other's waves
        self._wave_seq = itertools.count(1)
        self._token_prefix = f"{scheduler_name}#{next(_INSTANCE_SEQ)}"
        # fleet mode (round 18): when set, every wave/bind write carries
        # the instance's live partition-lease fencing tokens — a write
        # from a superseded claim is rejected whole by the store
        # (FencedError) and its pods are dropped to the claim's new
        # holder instead of re-queued
        self.fence_provider: Optional[Callable[[], Optional[list]]] = None
        self.fenced_waves = 0
        # crash-restart recovery context: while a burst's windows commit,
        # this tracks the exact walk-counter/rotation boundary of the
        # committed prefix plus the window in flight — recover() reads it
        # to resume with decisions matching an oracle that never crashed
        self._crash_ctx: Optional[dict] = None
        services = self.informers.informer(SERVICES)
        replicasets = self.informers.informer(REPLICASETS)
        self._services_fn = services.list
        self._replicasets_fn = replicasets.list
        # volume-aware scheduling (volumebinder bridge)
        self.volume_listers = VolumeListers(
            pvcs_fn=self.informers.informer(PVCS).list,
            pvs_fn=self.informers.informer(PVS).list)
        self.volume_binder = VolumeBinder(self.volume_listers, store=store)
        # gang scheduling: the PodGroup informer (registered here so
        # sync()/pump() carry it) + first-sighting times for the
        # wait-duration histogram when a group has no creation timestamp
        self._podgroups = self.informers.informer(PODGROUPS)
        self._gang_first_seen: dict[str, float] = {}
        self._predicate_names = predicate_names
        self._priority_weights = priority_weights
        # encode-at-admission pod-row cache (round 17): per-pod feature
        # rows + interned class signatures are computed ONCE at informer
        # delivery and gathered at window planning, instead of re-encoded
        # on every window's critical path. Only the TPU burst algorithm
        # reads it (the oracle shell decides per pod anyway); the
        # bit-identity contract (cached row == fresh encode, pod_rows
        # fuzz) keeps decisions oracle-parity by construction.
        self.pod_rows = None
        self.extenders = extenders or []
        self._extender_binder = next(
            (e for e in self.extenders if e.is_binder), None)
        decision_extenders = [
            e for e in self.extenders
            if e.config.filter_verb or e.config.prioritize_verb
            or e.config.preempt_verb]
        if use_tpu and decision_extenders and algorithm is None:
            # decision-affecting extenders need per-node host_priority and
            # HTTP round trips the device path doesn't model; silently
            # ignoring them would change decisions, so route scheduling
            # through the oracle instead (bind-only extenders keep the TPU
            # path: binding already goes through _extender_binder)
            import warnings
            warnings.warn("filter/prioritize extenders configured: scheduling "
                          "runs on the oracle path, not the TPU kernel path")
            use_tpu = False
        if algorithm is not None:
            self.algorithm = algorithm
        elif use_tpu:
            from kubernetes_tpu.core.tpu_scheduler import TPUScheduler
            self.algorithm = TPUScheduler(
                percentage_of_nodes_to_score=percentage_of_nodes_to_score,
                hard_pod_affinity_weight=hard_pod_affinity_weight,
                services_fn=self._services_fn,
                replicasets_fn=self._replicasets_fn,
                nominated=self.queue.nominated,
                volume_listers=self.volume_listers,
                volume_binder=self.volume_binder,
                node_tree=self.cache.node_tree,
                # single-pod cycles pick host-twin vs device by measured
                # latency (a tunneled chip's dispatch RTT dwarfs small-N
                # host scoring; decisions are identical either way)
                serial_path="adaptive",
                # "auto" shards the node axis over every visible chip
                # (parallel/sharding.py); the factory/CLI path opts in
                mesh=mesh,
                # the shell only consumes the suggested host + failure
                # reasons; skipping the per-node score readback saves a
                # full-vector transfer every cycle (extenders, which do read
                # host_priority, run on the oracle path)
                collect_host_priority=False)
            self.algorithm.metrics = self.metrics   # encode/kernel/fetch phases
            from kubernetes_tpu.ops.pod_rows import PodRowCache
            self.pod_rows = PodRowCache(
                profile_fn=(profiles.index_of if profiles is not None
                            else None))
            self.algorithm.pod_rows = self.pod_rows
            if profiles is not None:
                self.algorithm.set_profiles(profiles)
            if hasattr(store, "contains"):
                # mid-burst node-death detection: the wave drivers scan
                # each launch's decisions against the store after the
                # packed fetch and refuse the launch whole (StaleNodeRefusal
                # -> _burst_segment invalidates + replans) when a node
                # vanished under it
                self.algorithm.stale_scan = self._stale_scan
            if priority_weights is not None:
                from kubernetes_tpu.factory import tpu_kernel_weights
                self.algorithm.weights = tpu_kernel_weights(priority_weights)
                self.algorithm.priority_name_weights = priority_weights
            if predicate_names is not None:
                self.algorithm.enabled_predicates = set(predicate_names)
                self.algorithm.check_resources = bool(
                    {"GeneralPredicates", "PodFitsResources"} & set(predicate_names))
        else:
            self.algorithm = GenericScheduler(
                percentage_of_nodes_to_score=percentage_of_nodes_to_score,
                hard_pod_affinity_weight=hard_pod_affinity_weight,
                nominated_pods_fn=self.queue.nominated.pods_for_node)
            self.algorithm.extenders = self.extenders
        if profiles is not None:
            # per-profile PriorityConfig lists (the oracle/serial scoring
            # side of the tensor rows — same vectors, pinnable parity)
            self._profile_configs = [
                profiles.oracle_configs(
                    i, services_fn=self._services_fn,
                    replicasets_fn=self._replicasets_fn,
                    hard_pod_affinity_weight=hard_pod_affinity_weight)
                for i in range(len(profiles))]
            self._priority_configs = self._profile_configs[0]
        elif priority_weights is not None:
            from kubernetes_tpu.factory import build_priority_configs
            self._profile_configs = None
            self._priority_configs = build_priority_configs(
                priority_weights, services_fn=self._services_fn,
                replicasets_fn=self._replicasets_fn,
                hard_pod_affinity_weight=hard_pod_affinity_weight)
        else:
            self._profile_configs = None
            self._priority_configs = default_priority_configs(
                services_fn=self._services_fn, replicasets_fn=self._replicasets_fn,
                hard_pod_affinity_weight=hard_pod_affinity_weight)
        # plugin framework (framework/v1alpha1: registry -> per-point slices)
        self.framework = Framework(
            plugin_registry if plugin_registry is not None else Registry(),
            plugin_args=plugin_args,
            snapshot_fn=lambda: self._snapshot.node_infos,
            store=store, enabled=plugins_enabled)
        self._add_all_event_handlers()
        self._register_debug()

    def _note_profile_scheduled(self, pods: list) -> None:
        """Book successful bindings on the per-profile scheduled counter
        (scheduler_profile_scheduled_total + the /debug/sched section)."""
        if self.profiles is None:
            return
        for p in pods:
            pid = self.profiles.index_of(p.scheduler_name)
            if pid is not None:
                self.profiles.note_scheduled(pid)

    def _register_debug(self) -> None:
        """Publish this scheduler's /debug/sched sections (queue depths,
        parked gangs, device mirror, ledger) into the obs debug registry.
        Weakref-held: a dropped scheduler's section silently disappears
        instead of pinning the whole object graph (latest instance wins,
        matching the one-scheduler-per-process deployment shape)."""
        import weakref
        ref = weakref.ref(self)

        def snap():
            s = ref()
            if s is None:
                return None
            return s.debug_state()
        obs.register_debug("scheduler", snap)
        # cluster_resource_utilization{resource}: callback gauges over
        # the live snapshot (read at collect time — /metrics and the
        # timeseries scraper see the CURRENT fill, no push cadence).
        # Latest scheduler wins per child, same as the debug sections.
        for res in ("cpu", "memory", "ephemeral_storage"):
            def _util_reader(r=res):
                s = ref()
                if s is None:
                    return float("nan")
                from kubernetes_tpu.cache.node_info import (
                    cluster_utilization)
                try:
                    return cluster_utilization(s._snapshot.node_infos)[r]
                except RuntimeError:
                    # snapshot dict mutating under the scrape thread:
                    # this window reads no-data, never a crash
                    return float("nan")
            CLUSTER_UTILIZATION.labels(res).set_function(_util_reader)
        if self.profiles is not None:
            # loaded profiles, weight rows, per-profile scheduled counts
            pref = weakref.ref(self.profiles)

            def psnap():
                ps = pref()
                return None if ps is None else ps.debug_state()
            obs.register_debug("profiles", psnap)

    def reload_profiles(self) -> None:
        """Re-derive every profile-dependent cache after a ProfileSet row
        write (the tuner's set_row): the per-profile oracle
        PriorityConfig lists AND the device-side weight tensor (the TPU
        algorithm's set_profiles clears _ptab/_wtab_dev/_union_weights/
        _profile_static so the next launch gathers the NEW rows). A
        serving scheduler that skips this keeps scoring with the stale
        tensor — the write is not live until reload."""
        if self.profiles is None:
            return
        self._profile_configs = [
            self.profiles.oracle_configs(
                i, services_fn=self._services_fn,
                replicasets_fn=self._replicasets_fn,
                hard_pod_affinity_weight=self.hard_pod_affinity_weight)
            for i in range(len(self.profiles))]
        self._priority_configs = self._profile_configs[0]
        set_prof = getattr(self.algorithm, "set_profiles", None)
        if set_prof is not None:
            set_prof(self.profiles)

    def debug_state(self) -> dict:
        from kubernetes_tpu.obs.ledger import LEDGER
        from kubernetes_tpu.cache.node_info import cluster_utilization
        out = {
            "name": self.name,
            "queue": self.queue.debug_state(),
            "ledger": LEDGER.debug_state(),
            "utilization": cluster_utilization(self._snapshot.node_infos),
        }
        algo_dbg = getattr(self.algorithm, "debug_state", None)
        if algo_dbg is not None:
            out["device"] = algo_dbg()
        store_dbg = getattr(self.store, "debug_state", None)
        if store_dbg is not None:
            out["store"] = store_dbg()
        return out

    # -- event handlers (reference: eventhandlers.go:319) --------------------
    def _responsible_for(self, pod: Pod) -> bool:
        if self.profiles is not None:
            # multi-profile responsibility: any profile in the set claims
            # the pod; an unknown schedulerName is REPORTED (counter +
            # event, once per uid) and refused — never silently scored by
            # the default profile
            if self.profiles.index_of(pod.scheduler_name) is None:
                self.profiles.report_unknown(pod, recorder=self.recorder)
                return False
            return True
        return pod.scheduler_name == self.name

    def _add_all_event_handlers(self) -> None:
        pods = self.informers.informer(PODS)
        # assigned pods -> cache
        pods.add_event_handler(
            on_add=self._add_pod_to_cache,
            on_update=self._update_pod_in_cache,
            on_delete=self._delete_pod_from_cache,
            on_delete_many=self._delete_pods_from_cache,
            filter_fn=lambda p: bool(p.node_name))
        # unassigned pods owned by this scheduler -> queue (adds, updates,
        # and deletes all arrive in informer run batches: one queue lock +
        # one native heap push / row-cache pass per batch, and the pod-row
        # cache encodes each row here — at delivery — so window planning
        # gathers instead of re-encoding)
        pods.add_event_handler(
            on_add=self._add_pod_to_queue,
            on_add_many=self._add_pods_to_queue,
            on_update=self._update_pod_in_queue,
            on_update_many=self._update_pods_in_queue,
            on_delete=self._delete_pod_from_queue,
            on_delete_many=self._delete_pods_from_queue,
            filter_fn=lambda p: not p.node_name and self._responsible_for(p))
        nodes = self.informers.informer(NODES)
        nodes.add_event_handler(
            on_add=self._add_node, on_update=self._update_node,
            on_delete=self._delete_node)
        # service/RS/PDB events wake the queue (eventhandlers.go:32-86)
        for kind in (SERVICES, REPLICASETS, PDBS):
            self.informers.informer(kind).add_event_handler(
                on_add=lambda _o: self.queue.move_all_to_active(),
                on_update=lambda _o, _n: self.queue.move_all_to_active(),
                on_delete=lambda _o: self.queue.move_all_to_active())

    def _add_pod_to_cache(self, pod: Pod) -> None:
        self.cache.add_pod(pod)
        self.queue.assigned_pod_added(pod)

    def _update_pod_in_cache(self, old: Pod, new: Pod) -> None:
        if self._skip_pod_update(old, new):
            return
        self.cache.update_pod(old, new)
        self.queue.assigned_pod_updated(new)

    def _skip_pod_update(self, old: Pod, new: Pod) -> bool:
        """Ignore self-inflicted updates on assumed pods — but only when the
        diff is limited to resourceVersion / nodeName / status-ish fields;
        real label/spec changes must reach the cache
        (reference: eventhandlers.go:275 skipPodUpdate)."""
        if not self.cache.is_assumed_pod(new):
            return False
        assumed = self.cache.get_pod(new)
        if assumed is None:
            return False

        def sanitize(p: Pod) -> Pod:
            # reference skipPodUpdate strips ResourceVersion, spec.NodeName,
            # and the ENTIRE status (eventhandlers.go:275-315) — kubelet
            # status writes (phase, conditions, startTime) on an assumed pod
            # must not look like real updates
            c = p.clone()
            c.resource_version = 0
            c.node_name = ""
            c.nominated_node_name = ""
            c.phase = "Pending"
            c.conditions = ()
            c.start_time = None
            return c

        return sanitize(assumed) == sanitize(new)

    def _delete_pod_from_cache(self, pod: Pod) -> None:
        self.cache.remove_pod(pod)
        self.queue.move_all_to_active()

    def _delete_pods_from_cache(self, pods: list) -> None:
        """Batched delete run (round 23): per-pod cache removal, then ONE
        move_all_to_active for the whole run — the per-event loop would
        re-walk the unschedulable map once per delete."""
        for pod in pods:
            self.cache.remove_pod(pod)
        self.queue.move_all_to_active()

    def _add_pod_to_queue(self, pod: Pod) -> None:
        if self.pod_rows is not None:
            self.pod_rows.insert(pod)
        self.queue.add(pod)

    def _add_pods_to_queue(self, pods: list) -> None:
        """Batched informer delivery: encode every row once, then ONE
        queue lock + one heap-core push for the whole batch."""
        if self.pod_rows is not None:
            self.pod_rows.insert_many(pods)
        self.queue.add_many(pods)

    def _update_pod_in_queue(self, old: Pod, new: Pod) -> None:
        if self.pod_rows is not None:
            # update-in-place: same uid, new resourceVersion — re-encode
            # at delivery so the window gathers the NEW spec's row
            self.pod_rows.insert(new)
        self.queue.update(old, new)

    def _update_pods_in_queue(self, pairs: list) -> None:
        """Batched informer update run (round 23): re-encode every row
        once, then ONE queue lock for the whole run."""
        if self.pod_rows is not None:
            self.pod_rows.insert_many([new for _old, new in pairs])
        self.queue.update_many(pairs)

    def _delete_pod_from_queue(self, pod: Pod) -> None:
        if self.pod_rows is not None:
            # covers real deletes AND the unassigned->assigned transition
            # (the filtering handler delivers it as a delete of the old
            # object): a bound or gone pod's row is never gathered again
            self.pod_rows.invalidate(pod)
        self.queue.delete(pod)

    def _delete_pods_from_queue(self, pods: list) -> None:
        """Batched informer delete run (round 23): one row-cache
        invalidation pass + ONE queue lock for the whole run."""
        if self.pod_rows is not None:
            self.pod_rows.invalidate_many(pods)
        self.queue.delete_many(pods)

    def _add_node(self, node: Node) -> None:
        self.cache.add_node(node)
        self.queue.move_all_to_active()

    def _update_node(self, old: Node, new: Node) -> None:
        self.cache.update_node(old, new)
        if self._node_scheduling_properties_changed(old, new):
            self.queue.move_all_to_active()

    @staticmethod
    def _node_scheduling_properties_changed(old: Node, new: Node) -> bool:
        """Reference: eventhandlers.go:424 — only allocatable / labels /
        taints / unschedulable / condition changes wake the queue."""
        return (old.allocatable != new.allocatable
                or old.labels != new.labels
                or old.taints != new.taints
                or old.unschedulable != new.unschedulable
                or old.conditions != new.conditions)

    def _delete_node(self, node: Node) -> None:
        self.cache.remove_node(node)

    # -- lifecycle -----------------------------------------------------------
    def sync(self) -> None:
        self.informers.sync_all()

    def pump(self) -> int:
        return self.informers.pump_all()

    # -- one cycle (reference: scheduleOne :438) ------------------------------
    def schedule_one(self, timeout: Optional[float] = 0.05) -> bool:
        """Pop + schedule + assume + bind one pod. Returns False when the
        queue stayed empty for `timeout`."""
        pod = self.queue.pop(timeout=timeout)
        if pod is None:
            return False
        if pod.deleted:
            # reference: scheduler.go:447 skip-deleting-pod event
            self.recorder.pod_event(pod, WARNING, "FailedScheduling",
                                    f"skip schedule deleting pod: {pod.key}")
            return True
        gk = pod_group_key(pod)
        if gk is not None:
            # a gang member must never schedule alone: gather the rest of
            # its group from the activeQ and run the atomic gang segment
            # (the serial loop and the burst loop share one gang path)
            members = [(pod, self.queue.scheduling_cycle)]
            members += self.queue.pop_group(gk)
            self._gang_segment(gk, members, bucket=len(members))
            return True
        self._process_one(pod, self.queue.scheduling_cycle)
        return True

    def _process_one(self, pod: Pod, cycle: int,
                     names: Optional[list[str]] = None) -> bool:
        """Schedule + assume + bind one already-popped pod. `names` reuses an
        already-consumed NodeTree enumeration (burst bookkeeping) instead of
        consuming a fresh one. Returns True when the pod was bound (or its
        bind was dispatched to a permit-waiting bind thread)."""
        start = self.clock.now()
        # utiltrace analog (generic_scheduler.go:185): per-cycle step
        # timeline, logged only when the cycle is slow. Spans for the
        # cycle land in the obs ring buffer regardless (bounded, cheap).
        cycle_trace = Trace(f"scheduling cycle {pod.key}",
                            threshold=self.slow_cycle_threshold)
        crashed = False
        try:
            return self._process_one_traced(pod, cycle, names, start,
                                            cycle_trace)
        except chaos.SchedulerCrash:
            crashed = True   # freeze the recovery context for recover()
            raise
        finally:
            if not crashed:
                # a completed (or ordinarily failed) cycle leaves no
                # window in flight — stale contexts must not survive it
                self._crash_ctx = None
            if cycle_trace.log_if_long():
                cycle_trace.emit_spans()

    def _process_one_traced(self, pod: Pod, cycle: int,
                            names: Optional[list[str]], start: float,
                            cycle_trace: Trace) -> bool:
        # mid-stream node death, serial twin: the node.dead seam's
        # pre-cycle crossing lands a kill HERE — before this cycle's
        # decision — and the reconciliation sweep folds any store-side
        # node deletion into the cache/tree/mirror immediately, so the
        # decision (and a FitError's preemption scan) runs against the
        # post-churn world exactly like a burst launch the stale scan
        # refused. O(1) when nothing died.
        chaos.node_dead_point("pre-cycle")
        if self._reconcile_node_deaths() and names is not None:
            # the enumeration the caller consumed (a refused burst's
            # pre-drawn walk, or a burst tail's) describes a world that
            # still contained the dead node: discard it and re-ground on
            # a fresh post-churn enumeration, exactly what a serial loop
            # that saw the death before this cycle would draw
            names = None
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        cycle_trace.step("snapshot updated")
        if names is None:
            # serial-cycle crash bracket: checkpoint the rotation BEFORE
            # this cycle's enumeration so a crash between decision and a
            # landed bind recovers to the pre-decision boundary (the
            # re-queued pod then re-derives the identical decision)
            tree_chk = self.cache.node_tree.checkpoint()
            self._ctx_open(tree_chk)
            names = self.cache.node_tree.list_names()
        self._last_names = names
        try:
            t_alg = self.clock.now()
            try:
                result = self._schedule(pod, names)
            finally:
                self.metrics.observe_phase("algorithm",
                                           self.clock.now() - t_alg)
                cycle_trace.step("scheduling algorithm")
                # ledger: the serial cycle has no separate device
                # dispatch/fetch boundary — one stamp keeps the per-pod
                # phase decomposition telescoping on every path
                from kubernetes_tpu.obs.ledger import LEDGER
                LEDGER.stamp_serial(pod.key)
        except FitError as err:
            self.metrics.observe("unschedulable")
            if not self.disable_preemption:
                t_pre = self.clock.now()
                self._preempt(pod, err)
                self.metrics.observe_phase("preemption",
                                           self.clock.now() - t_pre)
                cycle_trace.step("preemption")
            self._record_failure(pod, cycle, REASON_UNSCHEDULABLE, str(err))
            return False
        except Exception as err:
            self.metrics.observe("error")
            self._record_failure(pod, cycle, REASON_SCHEDULER_ERROR, str(err))
            raise
        if self._crash_ctx is not None:
            # window bracket for this cycle's bind: before = pre-decision
            # boundary, after = the advanced counters + one enumeration
            c = self._crash_ctx
            self._ctx_window(
                {"li0": c["li"], "lni0": c["lni"], "committed0": 0,
                 "li1": getattr(self.algorithm, "last_index", 0),
                 "lni1": getattr(self.algorithm, "last_node_index", 0),
                 "committed1": 1},
                [pod.key], [result.suggested_host])
        assumed = pod.clone()
        assumed.node_name = result.suggested_host
        ctx = PluginContext()
        if assumed.volumes:
            node = self._snapshot.node_infos[result.suggested_host].node
            reservations = self.volume_binder.assume_pod_volumes(assumed, node)
            ctx.write("volume-reservations", reservations)
        # Reserve point (scheduler.go:507)
        st = self.framework.run_reserve_plugins(ctx, assumed, result.suggested_host)
        if not st.is_success():
            # release whatever earlier reserve plugins took (the v1alpha1
            # reference skips this; later versions unreserve here too)
            self.framework.run_unreserve_plugins(ctx, assumed, result.suggested_host)
            self.metrics.observe("error")
            self._record_failure(pod, cycle, REASON_SCHEDULER_ERROR, st.message)
            return False
        try:
            self.cache.assume_pod(assumed)
        except Exception as err:
            self.framework.run_unreserve_plugins(ctx, assumed, result.suggested_host)
            self.metrics.observe("error")
            self._record_failure(pod, cycle, REASON_SCHEDULER_ERROR, str(err))
            return False
        self.queue.nominated.delete(pod)
        cycle_trace.step("pod assumed")
        # Permit may WAIT: when permit plugins exist, bind runs off the
        # scheduling thread like the reference's bind goroutine
        # (scheduler.go:523) so allow()/reject() can come from this loop
        if self.framework.permit:
            t = threading.Thread(
                target=self._bind,
                args=(assumed, result.suggested_host, pod, cycle, ctx),
                daemon=True)
            t.start()
            self._bind_threads.append(t)
            cycle_trace.step("binding dispatched")
            bound = True   # outcome unknown until the thread resolves
        else:
            bound = self._bind(assumed, result.suggested_host, pod, cycle,
                               ctx)
            cycle_trace.step("binding")
        e2e = self.clock.now() - start
        self.metrics.e2e_latency_sum += e2e
        self.metrics.e2e_duration.observe(e2e)
        return bound

    def wait_for_binds(self, timeout: float = 5.0) -> None:
        """Join outstanding async bind threads (test/shutdown helper)."""
        for t in self._bind_threads:
            t.join(timeout)
        self._bind_threads = [t for t in self._bind_threads if t.is_alive()]

    def _pod_priority_configs(self, pod: Pod) -> list:
        """The oracle-path PriorityConfig list for one pod: its profile's
        vector when profiles are configured, else the single set."""
        if self._profile_configs is not None:
            pid = self.profiles.index_of(pod.scheduler_name)
            return self._profile_configs[0 if pid is None else pid]
        return self._priority_configs

    def _schedule(self, pod: Pod, names: list[str],
                  extra_configs=None) -> ScheduleResult:
        if isinstance(self.algorithm, GenericScheduler):
            from kubernetes_tpu.factory import (
                build_predicate_set, DEFAULT_PREDICATE_NAMES)
            funcs = build_predicate_set(
                self._predicate_names or DEFAULT_PREDICATE_NAMES,
                self._snapshot.node_infos,
                volume_listers=self.volume_listers,
                volume_binder=self.volume_binder,
                services_fn=self._services_fn)
            cfgs = self._pod_priority_configs(pod)
            if extra_configs:
                cfgs = list(cfgs) + list(extra_configs)
            return self.algorithm.schedule(
                pod, self._snapshot.node_infos, names,
                predicate_funcs=funcs,
                priority_configs=cfgs)
        if extra_configs:
            # trial-scoped extra priorities (gang locality): the TPU
            # algorithm routes these through its host twin
            return self.algorithm.schedule(
                pod, self._snapshot.node_infos, names,
                extra_configs=extra_configs)
        return self.algorithm.schedule(pod, self._snapshot.node_infos, names)

    def _gang_schedule_fn(self, tracker: dict):
        """Member dispatch for a serial gang trial: rank-aware profiles
        append a GangLocalityPriority bound to the trial's LIVE zone
        counts (`tracker["zones"]`), weighted by the member's profile
        gang weight — the serial half of the fused kernel's per-segment
        zone-count carry. Placement-blind members dispatch unchanged."""
        if self.profiles is None:
            return self._schedule
        from kubernetes_tpu.oracle.generic_scheduler import PriorityConfig
        from kubernetes_tpu.oracle import priorities as prios

        def fn(pod: Pod, names: list[str]) -> ScheduleResult:
            gw = self.profiles.gang_weight_for(pod.scheduler_name)
            if not gw:
                return self._schedule(pod, names)
            cfg = PriorityConfig(
                "GangLocalityPriority", gw,
                function=lambda _p, nis, nodes: [
                    prios.gang_locality_map(tracker["zones"], nis[n.name])
                    for n in nodes])
            return self._schedule(pod, names, extra_configs=[cfg])

        return fn

    def _bind(self, assumed: Pod, host: str, orig: Pod, cycle: int,
              ctx: Optional[PluginContext] = None) -> bool:
        """Reference: the bind goroutine (scheduler.go:523) — Permit (may
        wait) + Prebind + store write + FinishBinding; on failure
        ForgetPod + Unreserve + re-queue. Returns True when the binding
        landed."""
        ctx = ctx or PluginContext()
        t_bind = self.clock.now()

        def fail(unschedulable: bool, message: str = "") -> None:
            self.cache.forget_pod(assumed)
            try:
                self.volume_binder.forget_pod_volumes(
                    ctx.read("volume-reservations"))
            except KeyError:
                pass
            self.framework.run_unreserve_plugins(ctx, assumed, host)
            self.metrics.observe("unschedulable" if unschedulable else "error")
            self._record_failure(
                orig, cycle,
                REASON_UNSCHEDULABLE if unschedulable else REASON_SCHEDULER_ERROR,
                message)

        # mid-cycle node death: the chaos seam may kill the target here,
        # and the stale check refuses the bind exactly like a NotFound
        # store write — forget + re-queue with backoff (the serial twin
        # of _commit_burst's per-wave stale-host check)
        chaos.node_dead_point("pre-bind")
        if self._host_is_stale(host):
            STALE_BINDS.inc()
            self._invalidate_dead_node(host)
            fail(False, f"{NODES}/{host} (node deleted before bind)")
            return False
        st = self.framework.run_permit_plugins(ctx, assumed, host)
        if not st.is_success():
            fail(st.code == FW_UNSCHEDULABLE, st.message)
            return False
        st = self.framework.run_prebind_plugins(ctx, assumed, host)
        if not st.is_success():
            fail(st.code == FW_UNSCHEDULABLE, st.message)
            return False
        try:
            try:
                self.volume_binder.bind_pod_volumes(
                    ctx.read("volume-reservations"))
            except KeyError:
                pass
            # crash seams bracketing the serial bind write (the same
            # process-death stand-in the wave commit carries)
            chaos.check("sched.crash")
            if self._extender_binder is not None \
                    and self._extender_binder.is_interested(assumed):
                # extender-managed binding (factory.go GetBinder: a binder
                # extender owns the write only for pods it manages)
                self._extender_binder.bind(assumed, host)
            else:
                self._store_bind_pod(assumed.key, host)
            chaos.check("sched.crash")
            self.cache.finish_binding(assumed)
            self.metrics.binding_count += 1
            self.metrics.binding_duration.observe(self.clock.now() - t_bind)
            self.metrics.observe_phase("binding", self.clock.now() - t_bind)
            self.metrics.observe("scheduled")
            self._note_profile_scheduled([assumed])
            # user-visible audit record (scheduler.go:433)
            self.recorder.pod_event(
                assumed, NORMAL, "Scheduled",
                f"Successfully assigned {assumed.key} to {host}")
            return True
        except chaos.SchedulerCrash:
            raise   # process-death stand-in: recovery, not re-queue
        except FencedError:
            # superseded partition claim: the write was rejected whole.
            # Forget silently and DROP the pod — it belongs to the
            # claim's new holder now; a zombie writing failure events
            # for it would be exactly the write fencing forbids.
            from kubernetes_tpu.fleet import BIND_CONFLICTS
            BIND_CONFLICTS.labels("fenced").inc()
            self.fenced_waves += 1
            self.cache.forget_pod(assumed)
            if self.pod_rows is not None:
                self.pod_rows.invalidate(assumed)
            return False
        except ConflictError as err:
            # rv-CAS bind loss (already bound by another scheduler): the
            # winner's binding stands; the loser re-queues with backoff —
            # _record_failure drops the requeue once the store shows the
            # pod bound, which is the usual case
            from kubernetes_tpu.fleet import BIND_CONFLICTS
            BIND_CONFLICTS.labels("requeued").inc()
            fail(False, f"rv-CAS bind conflict: {err}")
            return False
        except Exception as err:
            fail(False, str(err))
            return False

    def _store_bind_pod(self, pod_key: str, host: str):
        """The serial bind write, carrying the instance's partition-lease
        fencing tokens when fleet mode is on and the store's verb takes
        them (probed per call only on the fleet path — the solo hot path
        is the plain verb unchanged)."""
        if self.fence_provider is None:
            return self.store.bind_pod(pod_key, host)
        fence = self.fence_provider()
        if not fence:
            return self.store.bind_pod(pod_key, host)
        import inspect
        try:
            takes = "fence" in inspect.signature(
                self.store.bind_pod).parameters
        except (TypeError, ValueError):
            takes = False
        if takes:
            return self.store.bind_pod(pod_key, host, fence=fence)
        return self.store.bind_pod(pod_key, host)

    def _record_failure(self, pod: Pod, cycle: int,
                        reason: str = REASON_SCHEDULER_ERROR,
                        message: str = "") -> None:
        """Reference: scheduler.go:266 recordSchedulingFailure — re-queue
        (factory.go:643 MakeDefaultErrorFunc), emit a FailedScheduling
        event, and write the PodScheduled=False condition so the failure is
        visible to store watchers (factory.go:715)."""
        try:
            current = self.store.get(PODS, pod.key)
        except NotFoundError:
            self.queue.delete(pod)
            return
        if current.node_name:
            return
        self.queue.add_unschedulable_if_not_present(current, cycle)
        self.recorder.pod_event(pod, WARNING, "FailedScheduling",
                                message or reason)
        try:
            self.store.update_pod_condition(pod.key, PodCondition(
                type=POD_SCHEDULED, status=CONDITION_FALSE,
                reason=reason, message=message))
        except NotFoundError:
            pass

    # -- preemption (reference: scheduler.go:292 preempt) ----------------------
    def _preempt(self, pod: Pod, err: FitError) -> None:
        from kubernetes_tpu.oracle.preemption import Preemptor
        self.metrics.preemption_attempts += 1
        try:
            updated = self.store.get(PODS, pod.key)   # factory.go:732
        except NotFoundError:
            return
        names = getattr(self, "_last_names", list(self._snapshot.node_infos))
        result = None
        if not any(getattr(e.config, "preempt_verb", "")
                   for e in self.extenders) \
                and hasattr(self.algorithm, "preempt"):
            # device victim scan: one launch over all candidate nodes
            # (oracle-identical decisions; None = not expressible on device)
            result = self.algorithm.preempt(
                updated, self._snapshot.node_infos, names, err,
                self.informers.informer(PDBS).list())
        if result is None:
            preemptor = Preemptor(pdbs_fn=self.informers.informer(PDBS).list,
                                  extenders=self.extenders)
            from kubernetes_tpu.factory import (
                build_predicate_set, DEFAULT_PREDICATE_NAMES)
            predicate_set_fn = lambda infos: build_predicate_set(
                self._predicate_names or DEFAULT_PREDICATE_NAMES, infos,
                volume_listers=self.volume_listers,
                volume_binder=self.volume_binder,
                services_fn=self._services_fn)
            result = preemptor.preempt(
                updated, self._snapshot.node_infos, names,
                err, nominated_pods_fn=self.queue.nominated.pods_for_node,
                predicate_set_fn=predicate_set_fn)
        self._apply_preemption_result(pod, updated, result)

    def _apply_preemption_result(self, pod: Pod, updated: Pod, result) -> None:
        """Side effects of one preemption decision (the back half of the
        reference's preempt, scheduler.go:310-339): in-memory nomination,
        the NominatedNodeName API write, victim deletion + audit events,
        stale-nomination cleanup. Shared by the serial path and the batched
        pressure tail so the two cannot drift."""
        if result.node is not None:
            # in-memory nomination first (scheduler.go:310), then the API write
            self.queue.nominated.add(updated, result.node.name)
            try:
                self.store.set_nominated_node_name(pod.key, result.node.name)
            except NotFoundError:
                # matches the reference's early error return, which also
                # skips the nominated_to_clear loop (scheduler.go:313-318)
                self.queue.nominated.delete(updated)
                return
            for victim in result.victims:
                try:
                    self.store.delete(PODS, victim.key)
                except NotFoundError:
                    pass
                self.metrics.preemption_victims += 1
                # victim audit record (scheduler.go:325)
                self.recorder.pod_event(
                    victim, NORMAL, "Preempted",
                    f"by {updated.key} on node {result.node.name}")
        # nomination cleanup happens even when no node was found: Preempt may
        # return the preemptor itself so its stale NominatedNodeName is
        # removed (scheduler.go:329-339)
        for p in result.nominated_to_clear:
            self.queue.nominated.delete(p)
            try:
                self.store.set_nominated_node_name(p.key, "")
            except NotFoundError:
                pass

    # -- burst mode (TPU throughput path) -------------------------------------
    def _pod_is_burstable(self, pod: Pod, services=None, replicasets=None) -> bool:
        """A pod may ride a device burst unless its per-node state depends
        on in-burst placements in a way no burst kernel models yet — only
        volume binding remains. Affinity/port/spread pods are admitted: the
        kernels fold their interactions (self-node bans, carried spread
        counts) and refuse anything they can't replay exactly."""
        if pod.volumes:
            return False
        return True

    def _burst_class(self, pod: Pod, services, replicasets):
        """Segmentation key: pods with in-burst-dynamic features (affinity /
        host ports / selector-spread) burst only with spec-identical peers
        (the kernels' eligibility contract); plain pods share one generic
        segment even when heterogeneous."""
        from kubernetes_tpu.api.types import (
            has_pod_affinity_terms, get_container_ports)
        from kubernetes_tpu.oracle.priorities import get_selectors
        if has_pod_affinity_terms(pod) or get_container_ports(pod) \
                or get_selectors(pod, services, replicasets):
            from kubernetes_tpu.core.tpu_scheduler import TPUScheduler
            return TPUScheduler._class_signature(pod)
        return "plain"

    def schedule_burst(self, max_pods: int = 1024) -> int:
        """Drain up to max_pods from the queue and schedule them with device
        bursts where safe, serially otherwise — decisions identical to the
        serial loop. PodGroup members collapse into atomic gang segments
        (all-or-nothing placement; see _gang_segment). Returns pods bound,
        derived from the commit paths' actual bound counts (not a
        schedule_attempts metric delta, which a concurrent metric observer
        — or reset() — could skew)."""
        total = 0
        for _pass in range(64):
            bound, drained = self._schedule_burst_pass(max_pods)
            total += bound
            if bound > 0 or drained == 0:
                return total
            # the pass drained pods but bound none — e.g. a rejected gang
            # consumed the whole drain window and parked: every drained pod
            # left the activeQ (parked/backed off), so ready singletons
            # behind the gang drain on the next pass instead of waiting for
            # the caller's next call. The activeQ strictly shrinks across
            # zero-bound passes (a real-clock backoff expiring mid-call can
            # re-admit a gang, hence the pass cap rather than `while True`).
        return total

    def _schedule_burst_pass(self, max_pods: int) -> tuple[int, int]:
        """One drain+schedule pass; returns (pods bound, pods drained)."""
        drained = []
        for pod, cycle in self.queue.pop_burst(max_pods):
            if pod.deleted:
                # same audit record as the serial path (scheduler.go:447)
                self.recorder.pod_event(
                    pod, WARNING, "FailedScheduling",
                    f"skip schedule deleting pod: {pod.key}")
                continue
            drained.append((pod, cycle))
        if not drained:
            return 0, 0
        # gang gathering: a group's members collapse into ONE atomic item at
        # the position of the group's first member (the queue's group-anchor
        # ordering makes them adjacent; collapsing is robust to interleaving
        # regardless), and members the drain limit cut off are pulled from
        # the activeQ so gangs are always attempted whole
        items: list = []
        gang_at: dict[str, int] = {}
        for pod, cycle in drained:
            gk = pod_group_key(pod)
            if gk is None:
                items.append((pod, cycle))
                continue
            idx = gang_at.get(gk)
            if idx is None:
                gang_at[gk] = len(items)
                items.append([gk, [(pod, cycle)]])
            else:
                items[idx][1].append((pod, cycle))
        for gk, idx in gang_at.items():
            items[idx][1].extend(self.queue.pop_group(gk))
        # fused planning (round 10): consecutive plain singleton runs and
        # eligible plain gangs collapse into ONE device launch + ONE packed
        # fetch (algorithm.schedule_burst_fused — gang boundaries become
        # scan segment boundaries). Anything the fused path can't express
        # (plugins, volumes, affinity/port/spread classes, incomplete or
        # missing groups, active nominations) keeps the per-segment
        # machinery, which knows how to park/degrade/serialize.
        fuse_ok = (getattr(self.algorithm, "supports_fused_segments", False)
                   and not self.framework.reserve
                   and not self.framework.permit
                   and not self.framework.prebind)
        services = self._services_fn()
        replicasets = self._replicasets_fn()

        # plain-burstable classification from the pod-row cache: one
        # np.take per flag field for the whole drain window instead of
        # per-pod predicate walks (selector-spread needs live service/RS
        # lists, so any registered selector source keeps the direct path;
        # flag values are bit-identical to the predicates by the row
        # contract — has_aff_terms/has_ports/has_volumes ARE those calls)
        plain_map = None
        if self.pod_rows is not None and not services and not replicasets:
            flat_drained = [p for p, _c in drained]
            g = self.pod_rows.gather(
                flat_drained, ("has_aff_terms", "has_ports", "has_volumes"))
            if g is not None:
                plain = ~(g["has_aff_terms"] | g["has_ports"]
                          | g["has_volumes"])
                plain_map = {id(p): bool(v)
                             for p, v in zip(flat_drained, plain)}

        def plain_burstable(pod: Pod) -> bool:
            if plain_map is not None:
                got = plain_map.get(id(pod))
                if got is not None:
                    return got
            return (self._pod_is_burstable(pod)
                    and self._burst_class(pod, services, replicasets)
                    == "plain")

        bound = 0
        window: list = []   # fused entries in queue order:
        wrun: list = []     # ("run", pairs) | ("gang", gk, group, members)
        srun: list = []     # non-fusable singleton accumulator

        def close_wrun() -> None:
            if wrun:
                window.append(("run", list(wrun)))
                wrun.clear()

        def flush_window() -> None:
            nonlocal bound
            close_wrun()
            if not window:
                return
            if any(e[0] == "gang" for e in window):
                bound += self._fused_window(window, max_pods)
            else:
                # no gang segment in the window: the ordinary burst path is
                # already one launch + one packed fetch per segment
                pairs = [pr for e in window for pr in e[1]]
                bound += self._schedule_singletons_burst(pairs, max_pods)
            window.clear()

        def flush_srun() -> None:
            nonlocal bound
            if srun:
                bound += self._schedule_singletons_burst(list(srun),
                                                         max_pods)
                srun.clear()

        for it in items:
            if isinstance(it, list):
                gk, members = it
                flush_srun()
                group = None
                if fuse_ok and not self.queue.nominated.has_any() \
                        and all(plain_burstable(p) for p, _c in members):
                    group = self._fusable_gang(gk, members)
                if group is not None:
                    close_wrun()
                    window.append(("gang", gk, group, members))
                else:
                    flush_window()
                    bound += self._gang_segment(gk, members,
                                                bucket=max_pods)
            elif fuse_ok and not self.queue.nominated.has_any() \
                    and plain_burstable(it[0]):
                flush_srun()
                wrun.append(it)
            else:
                flush_window()
                srun.append(it)
        flush_srun()
        flush_window()
        return bound, len(drained)

    def _schedule_singletons_burst(self, pairs: list, bucket: int) -> int:
        """Schedule a run of non-gang pods: device burst segments where
        safe, serial cycles otherwise (the pre-gang schedule_burst body)."""
        pods = [p for p, _ in pairs]
        cycles = [c for _, c in pairs]
        # the burst fold skips the per-pod Reserve/Permit/Prebind points, so
        # any configured plugin forces the serial path (decisions and plugin
        # side effects must not differ by path)
        can_burst = (hasattr(self.algorithm, "schedule_burst")
                     and not self.framework.reserve
                     and not self.framework.permit
                     and not self.framework.prebind)
        services = self._services_fn()
        replicasets = self._replicasets_fn()
        bound = 0
        i = 0
        while i < len(pods):
            # serial path for mask-stale pods and under active nominations
            # (the two-pass ghost check lives on the oracle path)
            if not can_burst or self.queue.nominated.has_any() \
                    or not self._pod_is_burstable(pods[i], services, replicasets):
                if self._process_one(pods[i], cycles[i]):
                    bound += 1
                i += 1
                continue
            seg_class = self._burst_class(pods[i], services, replicasets)
            j = i
            while j < len(pods) and not self.queue.nominated.has_any() \
                    and self._pod_is_burstable(pods[j], services, replicasets) \
                    and self._burst_class(pods[j], services,
                                          replicasets) == seg_class:
                j += 1
            bound += self._burst_segment(pods[i:j], cycles[i:j], bucket)
            i = j
        return bound

    # -- gang scheduling (coscheduling.PodGroup) ------------------------------
    def _gang_segment(self, group_key: str, members: list,
                      bucket: int) -> int:
        """All-or-nothing placement of one PodGroup's gathered members.

        The gang is trial-placed as ONE atomic burst segment through the
        existing wave machinery (schedule_burst with NO per-wave commit
        callback, so nothing reaches the cache or store mid-trial); the
        commit happens only when EVERY member found a node and the group's
        minMember is covered. Otherwise the in-flight device folds are
        discarded and li/lni + the NodeTree rotation cursor rewind to the
        pre-gang checkpoint (TPUScheduler.gang_rewind — PR 3's wave rewind
        contract generalized to per-group), no partial bind is ever
        observable, and the group parks in the queue's gang backoff map so
        queued singletons behind it are not starved. When the kernels
        refuse the gang's feature mix, the serial referee trial
        (oracle.gang.GangTrial) runs the SAME semantics pod by pod —
        decisions are bit-identical either way, which the gang parity fuzz
        pins. Returns pods bound."""
        pods = [p for p, _ in members]
        cycles = [c for _, c in members]
        try:
            group = self.store.get(PODGROUPS, group_key)
        except NotFoundError:
            group = None
        if group is None:
            # membership label without a PodGroup object: there is no gang
            # contract to enforce — members schedule as ordinary singletons
            # (create the PodGroup BEFORE its pods to get atomicity)
            self.queue.clear_group(group_key)
            return self._schedule_singletons_burst(members, bucket)
        now = self.clock.now()
        self._gang_first_seen.setdefault(group_key, now)
        if self.framework.reserve or self.framework.permit \
                or self.framework.prebind or any(p.volumes for p in pods):
            # per-pod extension points and volume reservations cannot be
            # rewound atomically: degrade to the per-pod path (documented
            # limitation — gangs compose with neither plugins nor volumes)
            GANG_ATTEMPTS.labels("degraded").inc()
            return self._schedule_singletons_burst(members, bucket)
        min_member = max(group.min_member, 1)
        from kubernetes_tpu.coscheduling.types import LABEL_POD_GROUP
        already_bound = sum(
            1 for p in self.informers.informer(PODS).list()
            if p.node_name and p.namespace == group.namespace
            and p.labels.get(LABEL_POD_GROUP) == group.name)
        if len(pods) + already_bound < min_member:
            # incomplete: not enough members exist/queued yet — park what is
            # here (phase PreScheduling; the PodGroup controller times the
            # group out to Unschedulable if it never fills)
            GANG_ATTEMPTS.labels("incomplete").inc()
            self._set_group_phase(group_key, PHASE_PRESCHEDULING, now)
            self._park_gang(group, pods,
                            f"waiting for minMember={min_member}: "
                            f"{already_bound} bound + {len(pods)} queued")
            return 0
        self._set_group_phase(group_key, PHASE_PRESCHEDULING, now)
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        tree = self.cache.node_tree
        hosts = None
        committed = 0
        # rank-aware gangs need the per-segment zone-count carry, which
        # only the fused segments kernel and the serial referee model —
        # the plain burst trial would score placement-blind, so it is
        # ineligible for them (the fused window path upstream is the
        # device home for rank-aware gangs)
        rank_aware = self.profiles is not None and any(
            self.profiles.gang_weight_for(p.scheduler_name) for p in pods)
        can_trial_burst = (hasattr(self.algorithm, "schedule_burst")
                           and not self.queue.nominated.has_any()
                           and not rank_aware
                           and all(self._pod_is_burstable(p) for p in pods))
        if can_trial_burst:
            has_gchk = hasattr(self.algorithm, "gang_checkpoint")
            chk = self.algorithm.gang_checkpoint() if has_gchk else (
                getattr(self.algorithm, "last_index", 0),
                getattr(self.algorithm, "last_node_index", 0))
            tree_chk = tree.checkpoint()
            self._ctx_open(tree_chk)
            names = tree.list_names()
            self._last_names = names
            hosts = self.algorithm.schedule_burst(
                pods, self._snapshot.node_infos, names, bucket=bucket)
            if hosts is not None and all(h is not None for h in hosts):
                dead = self._stale_scan(hosts, names)
                if dead:
                    # mid-burst node death during the gang trial: letting
                    # _commit_burst's wave filter fail just the stale
                    # members would bind a PARTIAL gang — rewind the trial
                    # whole (nothing committed), invalidate the dead
                    # nodes, and re-trial against the post-churn world
                    STALE_BINDS.inc(max(1, sum(1 for h in hosts
                                               if h in dead)))
                    if has_gchk:
                        self.algorithm.gang_rewind(chk)
                    else:
                        self.algorithm.last_index = chk[0]
                        self.algorithm.last_node_index = chk[1]
                        discard = getattr(self.algorithm,
                                          "discard_burst_folds", None)
                        if discard is not None:
                            discard()
                    tree.restore(tree_chk)
                    self._crash_ctx = None
                    for h in dead:
                        self._invalidate_dead_node(h)
                    return self._gang_segment(group_key, members,
                                              bucket=bucket)
                # crash bracket: the gang commits as ONE atomic window —
                # before = the pre-gang checkpoint, after = the post-trial
                # counters (a crash mid-commit recovers to whichever side
                # the store proves, never to a partial gang)
                ctx = self._crash_ctx
                self._ctx_window(
                    {"li0": ctx["li"], "lni0": ctx["lni"],
                     "committed0": 0,
                     "li1": getattr(self.algorithm, "last_index", 0),
                     "lni1": getattr(self.algorithm,
                                     "last_node_index", 0),
                     "committed1": len(pods)},
                    [p.key for p in pods], hosts)
                committed = self._commit_burst(pods, hosts, cycles)
                self._ctx_window_done()
                self._crash_ctx = None
                tree.advance_enumerations(len(pods) - 1)
            elif hosts is not None:
                # a member found no node: the gang is REJECTED — discard the
                # in-flight folds and rewind every carry to the pre-gang
                # checkpoint; nothing was committed
                if has_gchk:
                    self.algorithm.gang_rewind(chk)
                else:
                    # generic burst algorithm without the device checkpoint:
                    # rewind the walk counters and drop any resident folds
                    self.algorithm.last_index = chk[0]
                    self.algorithm.last_node_index = chk[1]
                    discard = getattr(self.algorithm,
                                      "discard_burst_folds", None)
                    if discard is not None:
                        discard()
                tree.restore(tree_chk)
                self._crash_ctx = None
                self._reject_gang(group, pods,
                                  sum(1 for h in hosts if h is not None))
                return 0
            else:
                # kernels refused this gang's feature mix: undo the consumed
                # enumeration and run the serial referee trial instead
                tree.restore(tree_chk)
                self._crash_ctx = None
        if hosts is None:
            # serial referee trial: per-member cycles with no packed-block
            # counters — crash recovery over this path is reconcile-only
            self._crash_ctx = None
            trial = GangTrial(self.cache, self.algorithm)

            def refresh():
                self._snapshot = self.cache.update_snapshot(self._snapshot)

            on_placed = None
            schedule_fn = self._schedule
            if rank_aware:
                # trial-scoped zone-count tracker: the serial half of the
                # fused kernel's gang set-scoring carry (a rollback
                # discards it with the trial)
                from kubernetes_tpu.api.types import get_zone_key
                tracker = {"zones": {}}
                schedule_fn = self._gang_schedule_fn(tracker)

                def on_placed(host: str) -> None:
                    ni = self._snapshot.node_infos.get(host)
                    if ni is not None and ni.node is not None:
                        z = get_zone_key(ni.node)
                        if z:
                            tracker["zones"][z] = \
                                tracker["zones"].get(z, 0) + 1

            hosts = trial.run(pods, schedule_fn, refresh,
                              on_placed=on_placed)
            if hosts is None:
                self._reject_gang(group, pods, 0)
                return 0
            dead = self._stale_scan(hosts, list(self._snapshot.node_infos))
            if dead:
                # same contract as the device trial: never bind a partial
                # gang across a node death — roll the trial's assumes back
                # and re-trial post-churn
                STALE_BINDS.inc(max(1, sum(1 for h in hosts if h in dead)))
                trial.rollback(trial.last_assumed, *trial.last_chk)
                for h in dead:
                    self._invalidate_dead_node(h)
                return self._gang_segment(group_key, members, bucket=bucket)
            committed = self._commit_burst(pods, hosts, cycles,
                                           assume=False)
        if committed < len(pods):
            # members vanished between trial and commit (deleted from the
            # store): the survivors are bound, the rest were forgotten and
            # re-queued by the commit path; the controller re-evaluates the
            # group against its live members
            GANG_ATTEMPTS.labels("error").inc()
        else:
            GANG_ATTEMPTS.labels("scheduled").inc()
        created = group.creation_timestamp \
            or self._gang_first_seen.get(group_key, now)
        GANG_WAIT.observe(max(0.0, self.clock.now() - created))
        self._gang_first_seen.pop(group_key, None)
        self.queue.clear_group(group_key)
        return committed

    def _set_group_phase(self, group_key: str, phase: str,
                         now: float) -> None:
        fn = getattr(self.store, "update_pod_group_status", None)
        if fn is None:
            return
        try:
            fn(group_key, phase=phase, now=now)
        except NotFoundError:
            pass

    def _reject_gang(self, group, pods: list, placed: int) -> None:
        """Book a rejected gang attempt: every member is unschedulable (the
        trial rewound, so none is bound) and the group parks as a unit.
        `placed` is how many members found nodes before the rewind."""
        GANG_ATTEMPTS.labels("rejected").inc()
        self.metrics.observe("unschedulable", count=len(pods))
        self._park_gang(
            group, pods,
            f"gang rejected: {placed}/{len(pods)} members found nodes "
            f"(minMember={group.min_member}); trial rewound")

    def _park_gang(self, group, pods: list, message: str) -> None:
        """Park a gang's still-pending members under the group backoff
        window, with the same failure observability the serial path gives
        one pod (FailedScheduling event + PodScheduled=False condition)."""
        alive = []
        for pod in pods:
            try:
                current = self.store.get(PODS, pod.key)
            except NotFoundError:
                self.queue.delete(pod)
                continue
            if current.node_name:
                continue
            alive.append(current)
        self.queue.park_group(group.key, alive)
        msg = f"pod group {group.key}: {message}"
        for p in alive:
            self.recorder.pod_event(p, WARNING, "FailedScheduling", msg)
            try:
                self.store.update_pod_condition(p.key, PodCondition(
                    type=POD_SCHEDULED, status=CONDITION_FALSE,
                    reason=REASON_UNSCHEDULABLE, message=msg))
            except NotFoundError:
                pass

    # -- fused drain windows (round 10) ---------------------------------------
    # test seam: when set, singleton runs inside a fused window are split
    # into scan segments of at most this many pods. Non-gang segment
    # boundaries are semantically inert (only gang segments rewind), so
    # this forces the kernel's checkpoint machinery across many small
    # segments without changing any decision — the segment-boundary fuzz
    # variants set it to 3/4.
    fused_run_split: Optional[int] = None

    def _fusable_gang(self, group_key: str, members: list):
        """A gang may ride a fused window only when the pre-trial host
        checks all pass: the PodGroup object exists, enough members are
        gathered (counting already-bound ones), and no member needs volume
        reservations. Everything else (missing group, incomplete,
        degraded) keeps the per-segment _gang_segment path, which knows
        how to park/degrade. Returns the PodGroup or None."""
        try:
            group = self.store.get(PODGROUPS, group_key)
        except NotFoundError:
            return None
        if group is None:
            return None
        pods = [p for p, _c in members]
        if any(p.volumes for p in pods):
            return None
        min_member = max(group.min_member, 1)
        from kubernetes_tpu.coscheduling.types import LABEL_POD_GROUP
        already_bound = sum(
            1 for p in self.informers.informer(PODS).list()
            if p.node_name and p.namespace == group.namespace
            and p.labels.get(LABEL_POD_GROUP) == group.name)
        if len(pods) + already_bound < min_member:
            return None
        return group

    def _fused_window(self, entries: list, bucket: int) -> int:
        """One launch + one packed fetch for a drain window that contains
        gang segments (algorithm.schedule_burst_fused): gang boundaries
        become device scan segment boundaries, rejected gangs rewind in
        the device carry and park host-side, and decided segments commit
        wave-by-wave out of the single fetched block. Falls back to the
        per-segment machinery when the algorithm refuses the window.
        Returns pods bound."""
        now = self.clock.now()
        if self.fused_run_split:
            split: list = []
            for e in entries:
                if e[0] != "run" or len(e[1]) <= self.fused_run_split:
                    split.append(e)
                    continue
                for lo in range(0, len(e[1]), self.fused_run_split):
                    split.append(("run",
                                  e[1][lo: lo + self.fused_run_split]))
            entries = split
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        tree = self.cache.node_tree
        tree_chk = tree.checkpoint()
        self._ctx_open(tree_chk)
        names = tree.list_names()
        self._last_names = names
        segments = []
        for e in entries:
            if e[0] == "gang":
                _kind, gk, group, members = e
                self._gang_first_seen.setdefault(gk, now)
                self._set_group_phase(gk, PHASE_PRESCHEDULING, now)
                segments.append(([p for p, _c in members], True))
            else:
                segments.append(([p for p, _c in e[1]], False))
        li0 = getattr(self.algorithm, "last_index", None)
        lni0 = getattr(self.algorithm, "last_node_index", None)
        res = self.algorithm.schedule_burst_fused(
            segments, self._snapshot.node_infos, names, bucket=bucket)
        if res is None:
            # window refused: undo the consumed enumeration and run every
            # entry through the per-segment paths
            tree.restore(tree_chk)
            self._crash_ctx = None
            return self._run_entries_unfused(entries, bucket)
        # mid-burst node death: a node deleted between this window's
        # snapshot and now (the node.dead seam fires between dispatch and
        # fetch, and between the fetch and the first wave commit) leaves
        # the fetched block holding decisions for a node that no longer
        # exists. NOTHING from the launch has committed yet, so the launch
        # refuses WHOLE: walk counters and the rotation walk rewind to the
        # pre-launch boundary, the dead node's cache entry, NodeTree slot,
        # device-mirror row, and victim-table row are invalidated, and the
        # same entries replan against the post-churn world — so the
        # decision stream stays bit-identical to a serial oracle that
        # observed the death before the same decisions (a fault costs
        # throughput, never a decision). Deletions landing after this
        # check are caught per-wave by _commit_burst's stale filter (the
        # requeue-with-backoff safety net).
        if li0 is not None:
            decided = [h for seg in res["segments"]
                       for h in (seg.get("hosts") or ())]
            dead = self._stale_scan(decided, names)
            if dead:
                STALE_BINDS.inc(max(1, sum(1 for h in decided
                                           if h in dead)))
                self.algorithm.fused_rewind(li0, lni0)
                tree.restore(tree_chk)   # exact: membership untouched yet
                self._crash_ctx = None
                for h in dead:
                    self._invalidate_dead_node(h)
                return self._fused_window(entries, bucket)
        bound = 0
        consumed = res["consumed"]
        aborted = False
        leftovers: list = []
        W = max(1, int(getattr(self.algorithm, "wave_size", 4096)))
        ctx = self._crash_ctx

        def seg_boundary(li1, lni1, t1) -> dict:
            """Window bracket from the committed-prefix boundary (ctx) to
            a segment/seq boundary — both sides exact on the fused path."""
            return {"li0": ctx["li"], "lni0": ctx["lni"],
                    "committed0": ctx["t"], "li1": int(li1),
                    "lni1": int(lni1), "committed1": int(t1)}

        def fold_boundary(li1, lni1, t1) -> None:
            ctx["li"], ctx["lni"], ctx["t"] = int(li1), int(lni1), int(t1)

        for e, seg in zip(entries, res["segments"]):
            status = seg["status"]
            if aborted or status == "undecided":
                leftovers.append(e)
                continue
            if e[0] == "gang":
                _kind, gk, group, members = e
                pods = [p for p, _c in members]
                cycles = [c for _p, c in members]
                if status == "rejected":
                    # the device carry already rewound; book the rejection
                    # exactly like a trial rewind (park under the group
                    # backoff, every member unschedulable). The rewound
                    # boundary (= pre-gang) is the new committed prefix.
                    self._reject_gang(group, pods, seg["placed"])
                    fold_boundary(seg["li"], seg["lni"], seg["t"])
                    continue
                # decided gang: ONE atomic commit for the whole group (a
                # wave window never splits a gang, so a crash between
                # windows cannot leave a partial gang bound)
                self._ctx_window(
                    seg_boundary(seg["li"], seg["lni"], seg["t"]),
                    [p.key for p in pods], seg["hosts"])
                committed = self._commit_burst(pods, seg["hosts"], cycles)
                self._ctx_window_done()
                bound += committed
                if committed < len(pods):
                    # members vanished between decision and commit: the
                    # survivors are bound, the rest were forgotten and
                    # re-queued — decisions past this segment assumed the
                    # missing folds, so stop consuming the block
                    GANG_ATTEMPTS.labels("error").inc()
                    self.algorithm.fused_rewind(seg["li"], seg["lni"])
                    consumed = seg["t"]
                    aborted = True
                else:
                    GANG_ATTEMPTS.labels("scheduled").inc()
                    created = group.creation_timestamp \
                        or self._gang_first_seen.get(gk, now)
                    GANG_WAIT.observe(max(0.0, self.clock.now() - created))
                self._gang_first_seen.pop(gk, None)
                self.queue.clear_group(gk)
            else:
                pairs = e[1]
                pods = [p for p, _c in pairs]
                cycles = [c for _p, c in pairs]
                hosts = seg["hosts"]   # decided prefix (all, unless failed)
                short_at = None
                for wlo in range(0, len(hosts), W):
                    hi = min(wlo + W, len(hosts))
                    self._ctx_window(
                        seg_boundary(seg["li_seq"][hi - 1],
                                     seg["lni_seq"][hi - 1],
                                     seg["t_seq"][hi - 1]),
                        [p.key for p in pods[wlo:hi]], hosts[wlo:hi])
                    n_b = self._commit_burst(pods[wlo:hi], hosts[wlo:hi],
                                             cycles[wlo:hi])
                    self._ctx_window_done()
                    bound += n_b
                    if n_b < hi - wlo:
                        short_at = hi
                        break
                if short_at is not None:
                    # short commit mid-run: rewind the walk counters to the
                    # end of the short window (its decisions were consumed,
                    # vanished pods re-queued) and discard the rest
                    self.algorithm.fused_rewind(
                        int(seg["li_seq"][short_at - 1]),
                        int(seg["lni_seq"][short_at - 1]))
                    consumed = int(seg["t_seq"][short_at - 1])
                    aborted = True
                    if short_at < len(pairs):
                        leftovers.append(("run", pairs[short_at:]))
                elif status == "failed" and len(hosts) < len(pairs):
                    # the run's tail (failing pod onward) reruns through
                    # the per-segment paths — its serial rerun may preempt
                    leftovers.append(("run", pairs[len(hosts):]))
        # serial semantics consume one NodeTree enumeration per decided
        # cycle; the kernel's consumed-count (rejected gangs rewound it) is
        # authoritative. Nothing decided -> the window's enumeration was
        # never used: restore it so the next cycle replays identically.
        if consumed > 0:
            tree.advance_enumerations(consumed - 1)
        else:
            tree.restore(tree_chk)
        self._crash_ctx = None   # window fully reconciled; nothing in flight
        if leftovers:
            bound += self._run_entries_unfused(leftovers, bucket)
        return bound

    def _run_entries_unfused(self, entries: list, bucket: int) -> int:
        """Process fused-window entries through the per-segment machinery
        (refused windows, and leftovers behind a failure/abort)."""
        bound = 0
        run: list = []
        for e in entries:
            if e[0] == "run":
                run.extend(e[1])
                continue
            if run:
                bound += self._schedule_singletons_burst(run, bucket)
                run = []
            bound += self._gang_segment(e[1], e[3], bucket=bucket)
        if run:
            bound += self._schedule_singletons_burst(run, bucket)
        return bound

    def _burst_segment(self, pods: list[Pod], cycles: list[int],
                       bucket: int) -> int:
        """Schedule one burst segment; returns pods bound."""
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        tree_chk = self.cache.node_tree.checkpoint()
        names = self.cache.node_tree.list_names()
        self._last_names = names
        self._ctx_open(tree_chk)
        # wave-window sink (tpu_scheduler.schedule_burst `commit`): the
        # algorithm fetches the whole burst's decisions as ONE packed
        # block and calls back with consecutive `wave_size` windows of
        # DECIDED hosts. A short commit (pods that vanished between
        # decision and commit) returns False, which makes the algorithm
        # stop consuming the block, rewind, and discard the rest.
        progress = {"committed": 0, "bound": 0, "failed": False}

        def commit_wave(lo: int, hosts: list) -> bool:
            k = len(hosts)
            # crash-restart window bracket: the algorithm's commit_marker
            # carries the exact walk counters at both window boundaries
            # (None fields where the packed block can't supply them)
            m = getattr(self.algorithm, "commit_marker", None)
            self._ctx_window(m, [p.key for p in pods[lo:lo + k]], hosts)
            n_bound = self._commit_burst(pods[lo:lo + k], hosts,
                                         cycles[lo:lo + k])
            self._ctx_window_done()
            progress["committed"] = lo + k
            progress["bound"] += n_bound
            if n_bound < k:
                progress["failed"] = True
                return False
            return True

        try:
            if getattr(self.algorithm, "supports_wave_commit", False):
                hosts = self.algorithm.schedule_burst(
                    pods, self._snapshot.node_infos, names, bucket=bucket,
                    commit=commit_wave)
            else:
                hosts = self.algorithm.schedule_burst(
                    pods, self._snapshot.node_infos, names, bucket=bucket)
        except StaleNodeRefusal as e:
            # mid-burst node death (round 14): the launch's decision block
            # references vanished nodes and was refused before any of its
            # decisions committed (the driver reconciled the committed
            # prefix — earlier chunks — and dropped its folds). Invalidate
            # the dead nodes everywhere and replan the uncommitted
            # remainder against the post-churn world: every surviving
            # decision is made with the node gone, exactly like a serial
            # loop that observed the death here.
            STALE_BINDS.inc(e.n_stale)
            done = progress["committed"]
            if done == 0:
                # the enumeration this segment consumed was never used
                self.cache.node_tree.restore(tree_chk)
            else:
                self.cache.node_tree.advance_enumerations(done - 1)
            self._crash_ctx = None
            for h in e.dead:
                self._invalidate_dead_node(h)
            return progress["bound"] + self._burst_segment(
                pods[done:], cycles[done:], bucket)
        if hosts is None:
            # the algorithm refused the whole burst (it can't reproduce the
            # serial walk for this cluster/workload; refusals happen before
            # any wave is dispatched or committed) — run pods one by one;
            # pod 0 rides the enumeration list_names() above already consumed
            # so every pod sees exactly its serial-loop node order
            bound = 0
            for i, (pod, cycle) in enumerate(zip(pods, cycles)):
                if self._process_one(pod, cycle,
                                     names=names if i == 0 else None):
                    bound += 1
            return bound
        kf = len(pods)
        if any(host is None for host in hosts):
            # burst contract (tpu_scheduler.schedule_burst): decisions from
            # the first None on are UNDECIDED — the algorithm rewound its
            # counters and device folds to the non-None prefix, whose
            # decisions are serial-exact and final. Commit the prefix, then
            # run the tail serially (a failing pod's serial rerun can
            # preempt — nominating a node and deleting victims — state the
            # discarded kernel decisions never saw).
            kf = hosts.index(None)
        done = progress["committed"]   # waves already committed in-flight
        bound = progress["bound"]
        if done < kf:
            bound += self._commit_burst(pods[done:kf], hosts[done:kf],
                                        cycles[done:kf])
        # serial semantics consume one NodeTree enumeration per pod; the
        # kernel modeled cycles 0..kf-1 on the segment's single
        # enumeration — fast-forward the rest of the committed prefix
        if kf > 0:
            self.cache.node_tree.advance_enumerations(kf - 1)
        # committed prefix fully reconciled: recovery past this point is
        # per-cycle (serial tail) or reconcile-only (pressure tail)
        self._crash_ctx = None
        if kf < len(pods):
            if progress["failed"]:
                # wave-commit failure: the algorithm discarded the in-flight
                # wave's decisions and its device folds (rewind contract) —
                # schedule the remainder as a fresh segment against a fresh
                # snapshot and enumeration (the forgotten pods re-queued)
                return bound + self._burst_segment(pods[kf:], cycles[kf:],
                                                   bucket)
            # the tail's first pod rides one fresh enumeration (or the
            # segment's own when the kernel decided nothing) whether it runs
            # batched or serial
            tail_names = names if kf == 0 \
                else self.cache.node_tree.list_names()
            tail_bound = self._try_pressure_tail(pods[kf:], cycles[kf:],
                                                 tail_names)
            if tail_bound is not None:
                return bound + tail_bound
            for k in range(kf, len(pods)):
                if self._process_one(pods[k], cycles[k],
                                     names=tail_names if k == kf else None):
                    bound += 1
        return bound

    # -- mid-burst node-death tolerance ---------------------------------------
    def _stale_scan(self, decided: list, names: list) -> set:
        """The launch-level node-death scan (wave drivers + fused window
        call it after the packed fetch, before the first commit): returns
        the set of nodes from this launch's world that no longer exist in
        the store. Decided hosts are probed individually (cheap, and the
        production-critical case — never bind to a dead node); a death
        whose rows received NO decisions still shifts rotation and
        tie-breaking, so a node-count shrink triggers the full-name probe.
        Stores without the O(1) count verb (remote) keep the decided-host
        probe only."""
        contains = getattr(self.store, "contains", None)
        if contains is None:
            return set()
        dead = {h for h in set(decided) if not contains(NODES, h)}
        if not dead:
            count = getattr(self.store, "count", None)
            if count is not None and count(NODES) < len(names):
                dead = {h for h in names if not contains(NODES, h)}
        return dead

    def _host_is_stale(self, host: str) -> bool:
        """True when the decision's target node no longer exists in the
        store (deleted between the packed fetch and this commit). Stores
        without the existence probe (no `contains`) skip the check — the
        bind write itself then resolves the race."""
        contains = getattr(self.store, "contains", None)
        return contains is not None and not contains(NODES, host)

    def _invalidate_dead_node(self, host: str) -> None:
        """Eagerly invalidate every decision structure referencing a node
        the store no longer has: the cache entry + NodeTree slot (the
        informer's DELETED event confirms later — both removals are
        idempotent) and the algorithm's device-mirror/victim-table rows.
        Runs in BOTH worlds (the oracle shell shares this path), so
        post-churn decision streams stay bit-identical: every subsequent
        cycle sees the node gone, whichever path detected it."""
        info = self._snapshot.node_infos.get(host)
        node = info.node if info is not None else None
        if node is None:
            # the snapshot can lag the cache (pre-cycle reconciliation
            # runs before the refresh) — the cache's object carries the
            # zone labels the NodeTree removal needs
            node = self.cache.get_node(host)
        if node is not None:
            self.cache.remove_node(node)
        inv = getattr(self.algorithm, "invalidate_node", None)
        if inv is not None:
            inv(host)

    def _reconcile_node_deaths(self) -> bool:
        """Serial twin of the launch-level stale scan: fold store-side
        node deletions the informers haven't delivered yet into the
        cache/tree/mirror before a serial cycle decides. O(1) (one store
        count) when nothing died; the informer's DELETED event later
        confirms — both removals are idempotent. Returns True when a
        death was found (the caller re-grounds any pre-drawn
        enumeration)."""
        count = getattr(self.store, "count", None)
        if count is None or not hasattr(self.store, "contains"):
            return False
        tree = self.cache.node_tree
        if count(NODES) >= tree.num_nodes:
            return False
        contains = self.store.contains
        found = False
        for host in tree.all_names():
            if not contains(NODES, host):
                self._invalidate_dead_node(host)
                found = True
        return found

    def _commit_burst(self, pods: list[Pod], hosts: list[str],
                      cycles: list[int], assume: bool = True) -> int:
        """Commit a burst's decided prefix (or one pipelined wave of it):
        ONE batched cache assume + vectorized device-mirror sync, then ONE
        batched store write for all bindings, one batched finish, one
        batched event write, and aggregated metrics — the per-pod
        lock/call overhead of the serial bind path amortized across the
        wave (VERDICT r4 weak #4: the 38us/pod host bind ceiling; the wave
        pipeline then hides what remains behind the next wave's device
        time). Pods an extender binder manages keep the per-pod path
        (extender-owned writes can't batch through our store). Returns the
        number of pods actually bound.

        Invariant: bursts only form when NO reserve/permit/prebind plugins
        are configured (schedule_burst's can_burst gate routes plugin-ful
        workloads to the serial _process_one/_bind path), so skipping the
        framework points here cannot skip real plugin work.

        `assume=False` is the serial-gang-trial commit: the members were
        already assumed one by one (oracle.gang.GangTrial), and nothing was
        folded on device, so both the batched cache assume AND the device-
        mirror sync are skipped — the cache generation bumps from the trial
        re-encode the touched rows on the next cycle instead."""
        if not pods:
            return 0
        assert not (self.framework.reserve or self.framework.permit
                    or self.framework.prebind), \
            "burst commit reached with framework plugins configured"
        # mid-burst node death (the round-14 tolerance contract): the
        # chaos seam may kill a node right here — between the packed
        # fetch and this wave's store write — and the stale-host check
        # then fails EXACTLY the decisions targeting vanished nodes:
        # those pods are never assumed, re-queue with backoff in creation
        # order (wave order is creation order), and the dead node's
        # mirror/victim/NodeTree rows invalidate eagerly. The short wave
        # count makes the burst driver abort + rewind, so undecided
        # successors reschedule against the post-churn world — the same
        # state a serial loop's failed bind leaves behind.
        chaos.node_dead_point("pre-bind")
        contains = getattr(self.store, "contains", None)
        if contains is not None:
            stale_hosts = {h for h in set(hosts) if not contains(NODES, h)}
            if stale_hosts:
                for h in stale_hosts:
                    self._invalidate_dead_node(h)
                live: list[tuple[Pod, str, int]] = []
                for pod, host, cycle in zip(pods, hosts, cycles):
                    if host not in stale_hosts:
                        live.append((pod, host, cycle))
                        continue
                    STALE_BINDS.inc()
                    self.metrics.observe("error")
                    self._record_failure(
                        pod, cycle, REASON_SCHEDULER_ERROR,
                        f"{NODES}/{host} (node deleted before bind)")
                pods = [p for p, _h, _c in live]
                hosts = [h for _p, h, _c in live]
                cycles = [c for _p, _h, c in live]
                if not pods:
                    return 0
        eb = self._extender_binder
        if eb is not None and any(eb.is_interested(p) for p in pods):
            n_bound = 0
            for pod, host, cycle in zip(pods, hosts, cycles):
                if assume:
                    assumed = self._assume_for_burst(pod, host)
                else:
                    assumed = pod.clone()
                    assumed.node_name = host
                if self._bind(assumed, host, pod, cycle):
                    n_bound += 1
            return n_bound
        t_bind = self.clock.now()
        assumed_list = []
        for pod, host in zip(pods, hosts):
            assumed = pod.clone()
            assumed.node_name = host
            assumed_list.append(assumed)
        if assume:
            self.cache.assume_pods(assumed_list)    # one lock for the wave
        note_many = getattr(self.algorithm, "note_burst_assumed_many", None) \
            if assume else None
        if note_many is not None:
            # the device scan already folded these deltas: sync the host
            # mirror + generation map in one vectorized pass (generations
            # read once, after every assume of the wave landed)
            note_many(assumed_list, hosts,
                      self.cache.node_generations(hosts))
        elif assume:
            note = getattr(self.algorithm, "note_burst_assumed", None)
            if note is not None:
                for assumed, host in zip(assumed_list, hosts):
                    gen = self.cache.node_generation(host)
                    if gen is not None:
                        note(assumed, host, gen)
        # the wave's whole store-write tail — batched binds PLUS the
        # Scheduled audit records for the binds that land — is ONE
        # commit-core call (native/commitcore.cpp or its Python twin);
        # watch fan-out is deliberately deferred to the ONE fanout_wave
        # call below so consumers copy events out while this thread
        # finishes the cache/metric tail (the call-count contract is
        # pinned by TestCommitWaveContract)
        bindings = [(a.key, h) for a, h in zip(assumed_list, hosts)]
        commit_wave = getattr(self.store, "commit_wave", None)
        emit_batch = commit_wave is None
        conflicted: list = []
        try:
            # crash seam, pre-write side: the wave has been assumed in the
            # cache but NOTHING reached the store — recovery must re-queue
            # every pod of this window
            chaos.check("sched.crash")
            if commit_wave is not None:
                missing_list, conflicted = self._commit_wave_retrying(
                    commit_wave, bindings)
                missing = set(missing_list)
            else:
                missing = set(self.store.bind_pods(bindings))
            # crash seam, post-write side: the wave LANDED but the cache
            # finish / metrics / fan-out tail never ran — recovery must
            # adopt every landed binding
            chaos.check("sched.crash")
        except chaos.SchedulerCrash:
            # the process-death stand-in must NOT be absorbed by the
            # graceful per-pod resolution below: it propagates to the test
            # harness, which then drives Scheduler.recover()
            raise
        except FencedError:
            # the partition lease this wave wrote under was superseded
            # mid-flight: the store rejected the WHOLE wave atomically
            # (nothing landed, no events). Forget the assumes and DROP
            # the pods — they belong to the claim's new holder, which
            # re-lists them from the store; a zombie must not keep
            # writing failure events/conditions for pods it lost.
            # (the finally below still runs the fan-out call)
            from kubernetes_tpu.fleet import BIND_CONFLICTS
            BIND_CONFLICTS.labels("fenced").inc(len(assumed_list))
            self.fenced_waves += 1
            for assumed in assumed_list:
                self.cache.forget_pod(assumed)
                if self.pod_rows is not None:
                    self.pod_rows.invalidate(assumed)
            return 0
        except Exception:
            # a mid-batch store failure may have partially committed:
            # resolve each pod by what actually landed — bound pods finish,
            # the rest forget + re-queue, exactly like the serial _bind's
            # per-pod failure handling (their audit records re-emit below;
            # fire-and-forget records tolerate the crash-path duplicate)
            from kubernetes_tpu.obs import flight as obs_flight
            obs_flight.RECORDER.note_crash("commit-wave-crash")
            emit_batch = True
            missing = set()
            for assumed, host in zip(assumed_list, hosts):
                try:
                    landed = self.store.get(PODS, assumed.key)
                except Exception:
                    # gone OR unreachable: either way the binding can't be
                    # confirmed — forget + re-queue (a pod that did land
                    # re-syncs as bound when the informer catches up)
                    missing.add(assumed.key)
                    continue
                if landed.node_name != host:
                    missing.add(assumed.key)
        finally:
            fanout = getattr(self.store, "fanout_wave", None)
            if fanout is not None:
                fanout()
        confl_set = set(conflicted)
        bound = []
        for assumed, pod, host, cycle in zip(assumed_list, pods, hosts,
                                             cycles):
            if assumed.key in confl_set:
                # rv-CAS bind loss: another scheduler bound this pod
                # between decision and commit (claim handoff window /
                # nominated race). The existing binding stands; the loser
                # forgets its assume and re-queues with backoff in
                # creation order — _record_failure reads the store and
                # drops the requeue when the pod is (as usual) already
                # bound by the winner.
                from kubernetes_tpu.fleet import BIND_CONFLICTS
                BIND_CONFLICTS.labels("requeued").inc()
                self.cache.forget_pod(assumed)
                self.metrics.observe("error")
                self._record_failure(
                    pod, cycle, REASON_SCHEDULER_ERROR,
                    f"{PODS}/{assumed.key} (rv-CAS bind conflict: bound "
                    f"by another scheduler)")
                continue
            if assumed.key in missing:
                # vanished between decision and commit: same handling as a
                # failed bind write (_bind's fail path)
                self.cache.forget_pod(assumed)
                self.metrics.observe("error")
                self._record_failure(pod, cycle, REASON_SCHEDULER_ERROR,
                                     f"{PODS}/{assumed.key}")
                continue
            bound.append((assumed, host))
        k = len(bound)
        if not k:
            return 0
        self.cache.finish_bindings([a for a, _h in bound])  # one lock
        dt = self.clock.now() - t_bind
        self.metrics.binding_count += k
        self.metrics.binding_duration.observe_many(dt / k, k)
        self.metrics.observe_phase("binding", dt / k, count=k)
        self.metrics.observe("scheduled", count=k)
        self._note_profile_scheduled([a for a, _h in bound])
        if emit_batch:
            # stores without the wave verb (and the crash-resolution path)
            # land audit records in one batched write (scheduler.go:433)
            self.recorder.pod_events_batch([
                (a, NORMAL, "Scheduled",
                 f"Successfully assigned {a.key} to {h}") for a, h in bound])
        return k

    def _commit_wave_retrying(self, commit_wave,
                              bindings: list) -> tuple[list, list]:
        """Idempotent commit_wave: bounded exponential backoff with jitter
        on transient store failures, under ONE dedupe token for the wave.
        A pre-land failure (nothing written) simply re-runs the wave; an
        AMBIGUOUS failure (the wave landed, the response was lost) is
        answered by the store's token map on retry — the wave can neither
        double-land nor double-emit its events. Exhausted retries fall
        back to the caller's per-pod crash resolution, which is also safe
        (it reads back what actually landed). Returns (missing keys,
        rv-CAS conflicted keys) — conflicted pods were bound by another
        scheduler between decision and commit and are NEVER overwritten.

        Stores whose commit_wave takes `event_spec` (round 17) build the
        wave's Scheduled records INSIDE the commit core — no per-pod
        record construction on this thread; older/alternate stores get
        host-built records (identical fields). Stores taking `fence`
        carry the instance's partition-lease tokens (fleet mode); a
        FencedError is DEFINITIVE (ConflictError is never a transient) —
        it propagates for the caller's whole-wave drop, never retried."""
        import inspect
        try:
            # probed per wave, not cached: tests (and alternate stores)
            # swap commit_wave at runtime
            params = inspect.signature(commit_wave).parameters
            takes_token = "token" in params
            takes_spec = "event_spec" in params
            takes_fence = "fence" in params
            takes_conflicts = "conflicts" in params
        except (TypeError, ValueError):
            takes_token = takes_spec = False
            takes_fence = takes_conflicts = False
        kwargs = {}
        if takes_token:
            kwargs["token"] = f"{self._token_prefix}:w{next(self._wave_seq)}"
        if takes_fence and self.fence_provider is not None:
            fence = self.fence_provider()
            if fence:
                kwargs["fence"] = fence
        if takes_spec:
            recs = None
            kwargs["event_spec"] = {"component": self.recorder.component}
        else:
            from kubernetes_tpu.api.types import EventRecord
            from kubernetes_tpu.store.record import (
                build_scheduled_records, reserve_seq)
            recs = build_scheduled_records(
                EventRecord, bindings, self.recorder.component,
                reserve_seq(max(1, len(bindings))))
        delay = 0.005
        attempts = 4
        for attempt in range(attempts):
            confl: list = []
            if takes_conflicts:
                # a FRESH list per attempt: a dedupe-answered retry
                # extends it from the recorded wave result
                kwargs["conflicts"] = confl
            try:
                out = commit_wave(bindings, recs, **kwargs)
                if attempt:
                    COMMIT_RETRIES.labels("recovered").inc()
                return out, confl
            except Exception as e:   # noqa: BLE001 — filtered below
                if attempt + 1 >= attempts \
                        or not _retryable_store_error(e):
                    if attempt:
                        COMMIT_RETRIES.labels("exhausted").inc()
                    raise
                COMMIT_RETRIES.labels("retried").inc()
                time.sleep(delay * (0.5 + (attempt % 2) / 2))
                delay *= 2

    def _assume_for_burst(self, pod: Pod, host: str) -> Pod:
        assumed = pod.clone()
        assumed.node_name = host
        self.cache.assume_pod(assumed)
        note = getattr(self.algorithm, "note_burst_assumed", None)
        if note is not None:
            # the device scan already folded this delta: sync the host
            # mirror + generation map so the next encode() skips the row
            gen = self.cache.node_generation(host)
            if gen is not None:
                note(assumed, host, gen)
        return assumed

    def _try_pressure_tail(self, pods: list[Pod], cycles: list[int],
                           names: list[str]) -> Optional[int]:
        """Run a failed burst tail through the batched schedule-else-preempt
        launch (algorithm.preempt_pressure_burst) instead of one serial
        cycle + victim scan per pod. Returns None when the batch isn't
        applicable — the caller falls back to the serial loop — else the
        number of pods bound. Decisions and store/queue side effects are
        identical to the serial path (the batched-kernel gates + shared
        _apply_preemption_result guarantee it; the pressure parity fuzzes
        are the tripwire)."""
        fn = getattr(self.algorithm, "preempt_pressure_burst", None)
        if fn is None or self.disable_preemption or self.extenders:
            return None
        if self.queue.nominated.has_any():
            return None
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        self._last_names = names
        t_launch = self.clock.now()
        outcomes = fn(pods, self._snapshot.node_infos, names,
                      self.informers.informer(PDBS).list())
        if outcomes is None:
            return None
        # metric-shape parity with the serial loop: every pod gets an
        # "algorithm" phase sample (its share of the one launch), failed
        # pods a "preemption" sample, bound pods an e2e sample — so the
        # per-phase histograms keep comparable shapes whichever
        # (decision-identical) path ran
        share = (self.clock.now() - t_launch) / max(len(pods), 1)
        from kubernetes_tpu.oracle.preemption import PreemptionResult
        note = getattr(self.algorithm, "note_burst_assumed", None)
        n = len(names)
        n_bound = 0
        for pod, cycle, oc in zip(pods, cycles, outcomes):
            t_pod = self.clock.now()
            self.metrics.observe_phase("algorithm", share)
            if oc[0] == "bound":
                host = oc[1]
                assumed = pod.clone()
                assumed.node_name = host
                self.cache.assume_pod(assumed)
                if note is not None:
                    gen = self.cache.node_generation(host)
                    if gen is not None:
                        note(assumed, host, gen)
                self.queue.nominated.delete(pod)
                if self._bind(assumed, host, pod, cycle):
                    n_bound += 1
                e2e = share + (self.clock.now() - t_pod)
                self.metrics.e2e_latency_sum += e2e
                self.metrics.e2e_duration.observe(e2e)
                continue
            self.metrics.observe("unschedulable")
            self.metrics.preemption_attempts += 1
            try:
                updated = self.store.get(PODS, pod.key)   # factory.go:732
            except NotFoundError:
                updated = None
            if updated is not None:
                if oc[0] == "nominated":
                    node = self._snapshot.node_infos[oc[1]].node
                    result = PreemptionResult(node, oc[2], [])
                else:
                    # no candidate nodes at all: the oracle returns the pod
                    # itself so its stale nomination is cleared (:330-333)
                    result = PreemptionResult(
                        None, [], [] if oc[1] else [updated])
                self._apply_preemption_result(pod, updated, result)
            self.metrics.observe_phase("preemption",
                                       self.clock.now() - t_pod)
            self._record_failure(pod, cycle, REASON_UNSCHEDULABLE,
                                 str(FitError(pod, n, {})))
        # the kernel modeled one enumeration per pod on the axis order
        # (identity rotation is a batch gate); consume the remainder
        self.cache.node_tree.advance_enumerations(len(pods) - 1)
        return n_bound

    # -- crash-restart warm recovery ------------------------------------------
    # The recovery context brackets every committed burst window with the
    # exact walk-counter / NodeTree boundary on each side. A crash
    # (chaos.SchedulerCrash — the process-death stand-in — escaping the
    # commit path) freezes it; recover() reads the store to learn which
    # side of the in-flight window actually landed and rewinds/advances
    # the decision state to exactly where an oracle that never crashed
    # would be, then reconciles cache/queue/nominations from a relist.
    def _ctx_open(self, tree_chk) -> None:
        """Open a burst recovery context at the segment's pre-enumeration
        boundary (tree checkpoint taken BEFORE list_names)."""
        self._crash_ctx = {
            "tree_chk": tree_chk,
            "li": getattr(self.algorithm, "last_index", 0),
            "lni": getattr(self.algorithm, "last_node_index", 0),
            "t": 0, "exact": True, "window": None,
        }

    def _ctx_window(self, marker: Optional[dict], keys: list,
                    hosts: list) -> None:
        """Bracket one commit window: `marker` is the algorithm's
        commit_marker (exact boundary counters where the packed block
        carries them; None fields degrade recovery to reconcile-only)."""
        ctx = self._crash_ctx
        if ctx is None:
            return
        m = marker or {}
        ctx["window"] = {
            "keys": list(keys), "hosts": list(hosts),
            "li0": m.get("li0"), "lni0": m.get("lni0"),
            "li1": m.get("li1"), "lni1": m.get("lni1"),
            "t0": m.get("committed0"), "t1": m.get("committed1"),
        }

    def _ctx_window_done(self) -> None:
        """Fold a successfully committed window into the context's
        committed-prefix boundary."""
        ctx = self._crash_ctx
        if ctx is None or ctx["window"] is None:
            return
        w = ctx.pop("window")
        ctx["window"] = None
        if w["li1"] is None or w["lni1"] is None or w["t1"] is None:
            ctx["exact"] = False
        else:
            ctx["li"], ctx["lni"], ctx["t"] = w["li1"], w["lni1"], w["t1"]

    def recover(self) -> dict:
        """Crash-restart warm recovery (the reference's restart story —
        factory.go:643 re-queue, re-list on restart — compressed into one
        in-process path, plus the device state a restarted TPU scheduler
        must rebuild):

        1. decide the commit boundary: when a burst window was in flight,
           read the store to learn whether it landed (commit_wave is
           atomic per window: all its binds or none), and set the walk
           counters / NodeTree rotation to that side's exact boundary —
           the state an oracle that never crashed would hold;
        2. re-list every informer (authoritative store view; handlers
           reconcile caches/queue with DeltaFIFO Replace semantics);
        3. reconcile the scheduler cache: assumed-but-unbound pods are
           forgotten and RE-QUEUED (their assume died with the crash),
           assumed pods whose binding landed are ADOPTED (finish), bound
           pods the cache never saw are adopted via the relist;
        4. rebuild the nomination map from the store's
           nominatedNodeName fields;
        5. drop every device-resident structure (folds for uncommitted
           decisions, the victim table) — the next encode re-uploads from
           the now-authoritative host mirror.

        Returns a report dict (requeued/adopted keys, whether the walk
        counters were recovered exactly)."""
        self.wait_for_binds()
        report = {"requeued": [], "adopted": [], "exact": True,
                  "window_landed": None}
        # -- 1. commit boundary from the frozen context ----------------------
        ctx, self._crash_ctx = self._crash_ctx, None
        li = lni = t = None
        if ctx is not None:
            li, lni, t = ctx["li"], ctx["lni"], ctx["t"]
            exact = ctx["exact"]
            w = ctx.get("window")
            if w is not None:
                landed = False
                for key, host in zip(w["keys"], w["hosts"]):
                    try:
                        cur = self.store.get(PODS, key)
                    except NotFoundError:
                        continue
                    if cur.node_name == host:
                        landed = True
                        break
                report["window_landed"] = landed
                side = ("li1", "lni1", "t1") if landed \
                    else ("li0", "lni0", "t0")
                vals = [w[k] for k in side]
                if any(v is None for v in vals):
                    exact = False
                else:
                    li, lni, t = vals
            report["exact"] = exact
            if exact:
                tree = self.cache.node_tree
                tree.restore(ctx["tree_chk"])
                if t and t > 0:
                    # the committed prefix consumed t enumerations: one
                    # via list_names + (t-1) fast-forwards, mirroring the
                    # shell's own advance pattern
                    tree.list_names()
                    tree.advance_enumerations(t - 1)
            else:
                li = lni = None   # keep current counters; reconcile only
        # -- 2. authoritative relist -----------------------------------------
        for inf in list(self.informers._informers.values()):
            if inf.has_synced:
                inf._relist()
            else:
                inf.sync()
        # -- 3. cache reconcile ----------------------------------------------
        store_pods = {p.key: p for p in self.store.list(PODS)[0]}
        for assumed in self.cache.assumed_pods():
            cur = store_pods.get(assumed.key)
            if cur is not None and cur.node_name == assumed.node_name:
                # bound-but-unobserved: the write landed, the finish never
                # ran (or the informer skipped the self-inflicted update)
                self.cache.finish_binding(assumed)
                report["adopted"].append(assumed.key)
                continue
            # assumed-but-unbound (or bound elsewhere / deleted): the
            # assume died with the crash — forget it; the queue rebuild
            # below re-enters the live store object
            self.cache.forget_pod(assumed)
            if cur is not None and not cur.node_name \
                    and not cur.deleted and self._responsible_for(cur):
                report["requeued"].append(assumed.key)
        # -- 3b. activeQ rebuild from the relist ------------------------------
        # A restarted scheduler's queue is EMPTY: every pending pod
        # re-enters in creation order (the store lists in insertion
        # order), exactly the arrival order the never-crashed world's
        # informer fed its queue — so the post-restart pop order matches
        # the oracle's. This deliberately resets in-process backoff and
        # parked-gang state (it died with the process, as on a real
        # restart); pods mid-pop at the crash (the drained-but-undecided
        # burst tail) re-enter here too.
        pending = [cur for cur in store_pods.values()
                   if not cur.node_name and not cur.deleted
                   and self._responsible_for(cur)]
        for cur in pending:
            self.queue.delete(cur)
        for cur in pending:
            self.queue.add(cur)
        # -- 4. nominations ----------------------------------------------------
        for p in self.queue.nominated.all_pods():
            cur = store_pods.get(p.key)
            if cur is None or cur.node_name or not cur.nominated_node_name:
                self.queue.nominated.delete(p)
        for cur in store_pods.values():
            if not cur.node_name and cur.nominated_node_name:
                self.queue.nominated.add(cur)
        # -- 5. device state ---------------------------------------------------
        rec_dev = getattr(self.algorithm, "recover_device", None)
        if rec_dev is not None:
            rec_dev(li=li, lni=lni)
        else:
            if li is not None and hasattr(self.algorithm, "last_index"):
                self.algorithm.last_index = li
            if lni is not None \
                    and hasattr(self.algorithm, "last_node_index"):
                self.algorithm.last_node_index = lni
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        return report

    def run(self, stop_after: Optional[Callable[[], bool]] = None) -> None:
        """wait.Until(scheduleOne, 0) analog; call from a thread."""
        while not self._stop.is_set():
            self.pump()
            self.schedule_one()
            if stop_after is not None and stop_after():
                return

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
