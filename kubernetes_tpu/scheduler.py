"""Scheduler shell: owns the scheduling loop, one pod per cycle (or a burst
per launch), assume → bind pipeline, informer wiring, failure re-queue.

Mirrors pkg/scheduler/scheduler.go (New :121, Run :250, scheduleOne :438,
assume :382, bind :411, recordSchedulingFailure :266) and
pkg/scheduler/eventhandlers.go:319 AddAllEventHandlers. The algorithm is
pluggable: the oracle (pure Python, the parity referee) or the TPU kernel
path (core.TPUScheduler); binding I/O stays off the decision path like the
reference's bind goroutine (scheduler.go:523).
"""
from __future__ import annotations

import copy
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.types import Pod, Node
from kubernetes_tpu.cache.cache import SchedulerCache, Snapshot
from kubernetes_tpu.oracle.generic_scheduler import (
    GenericScheduler, FitError, ScheduleResult, default_priority_configs,
)
from kubernetes_tpu.queue.scheduling_queue import PriorityQueue
from kubernetes_tpu.store.store import (
    Store, PODS, NODES, SERVICES, REPLICASETS, PDBS, NotFoundError,
)
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.utils.clock import Clock, RealClock

DEFAULT_SCHEDULER_NAME = "default-scheduler"


@dataclass
class SchedulerMetrics:
    """Counter mirror of pkg/scheduler/metrics/metrics.go."""
    schedule_attempts: dict[str, int] = field(default_factory=lambda: {
        "scheduled": 0, "unschedulable": 0, "error": 0})
    binding_count: int = 0
    preemption_attempts: int = 0
    preemption_victims: int = 0
    e2e_latency_sum: float = 0.0

    def observe(self, result: str) -> None:
        self.schedule_attempts[result] = self.schedule_attempts.get(result, 0) + 1


class Scheduler:
    """One scheduler instance: queue + cache + algorithm + binder."""

    def __init__(self, store: Store,
                 scheduler_name: str = DEFAULT_SCHEDULER_NAME,
                 algorithm=None,
                 use_tpu: bool = False,
                 percentage_of_nodes_to_score: int = 50,
                 hard_pod_affinity_weight: int = 1,
                 clock: Optional[Clock] = None,
                 disable_preemption: bool = False):
        self.store = store
        self.name = scheduler_name
        self.clock = clock or RealClock()
        self.cache = SchedulerCache(clock=self.clock)
        self.queue = PriorityQueue(clock=self.clock)
        self.metrics = SchedulerMetrics()
        self.informers = InformerFactory(store)
        self.disable_preemption = disable_preemption
        self._snapshot = Snapshot()
        self._stop = threading.Event()
        services = self.informers.informer(SERVICES)
        replicasets = self.informers.informer(REPLICASETS)
        self._services_fn = services.list
        self._replicasets_fn = replicasets.list
        if algorithm is not None:
            self.algorithm = algorithm
        elif use_tpu:
            from kubernetes_tpu.core.tpu_scheduler import TPUScheduler
            self.algorithm = TPUScheduler(
                percentage_of_nodes_to_score=percentage_of_nodes_to_score,
                hard_pod_affinity_weight=hard_pod_affinity_weight,
                services_fn=self._services_fn,
                replicasets_fn=self._replicasets_fn)
        else:
            self.algorithm = GenericScheduler(
                percentage_of_nodes_to_score=percentage_of_nodes_to_score,
                hard_pod_affinity_weight=hard_pod_affinity_weight)
        self._priority_configs = default_priority_configs(
            services_fn=self._services_fn, replicasets_fn=self._replicasets_fn,
            hard_pod_affinity_weight=hard_pod_affinity_weight)
        self._add_all_event_handlers()

    # -- event handlers (reference: eventhandlers.go:319) --------------------
    def _responsible_for(self, pod: Pod) -> bool:
        return pod.scheduler_name == self.name

    def _add_all_event_handlers(self) -> None:
        pods = self.informers.informer(PODS)
        # assigned pods -> cache
        pods.add_event_handler(
            on_add=self._add_pod_to_cache,
            on_update=self._update_pod_in_cache,
            on_delete=self._delete_pod_from_cache,
            filter_fn=lambda p: bool(p.node_name))
        # unassigned pods owned by this scheduler -> queue
        pods.add_event_handler(
            on_add=self.queue.add,
            on_update=self._update_pod_in_queue,
            on_delete=self._delete_pod_from_queue,
            filter_fn=lambda p: not p.node_name and self._responsible_for(p))
        nodes = self.informers.informer(NODES)
        nodes.add_event_handler(
            on_add=self._add_node, on_update=self._update_node,
            on_delete=self._delete_node)
        # service/RS/PDB events wake the queue (eventhandlers.go:32-86)
        for kind in (SERVICES, REPLICASETS, PDBS):
            self.informers.informer(kind).add_event_handler(
                on_add=lambda _o: self.queue.move_all_to_active(),
                on_update=lambda _o, _n: self.queue.move_all_to_active(),
                on_delete=lambda _o: self.queue.move_all_to_active())

    def _add_pod_to_cache(self, pod: Pod) -> None:
        self.cache.add_pod(pod)
        self.queue.assigned_pod_added(pod)

    def _update_pod_in_cache(self, old: Pod, new: Pod) -> None:
        if self._skip_pod_update(old, new):
            return
        self.cache.update_pod(old, new)
        self.queue.assigned_pod_updated(new)

    def _skip_pod_update(self, old: Pod, new: Pod) -> bool:
        """Ignore self-inflicted updates on assumed pods
        (reference: eventhandlers.go:275 skipPodUpdate)."""
        if not self.cache.is_assumed_pod(new):
            return False
        # changes besides nominated-node/status are real
        return old.node_name == new.node_name

    def _delete_pod_from_cache(self, pod: Pod) -> None:
        self.cache.remove_pod(pod)
        self.queue.move_all_to_active()

    def _update_pod_in_queue(self, old: Pod, new: Pod) -> None:
        self.queue.update(old, new)

    def _delete_pod_from_queue(self, pod: Pod) -> None:
        self.queue.delete(pod)

    def _add_node(self, node: Node) -> None:
        self.cache.add_node(node)
        self.queue.move_all_to_active()

    def _update_node(self, old: Node, new: Node) -> None:
        self.cache.update_node(old, new)
        if self._node_scheduling_properties_changed(old, new):
            self.queue.move_all_to_active()

    @staticmethod
    def _node_scheduling_properties_changed(old: Node, new: Node) -> bool:
        """Reference: eventhandlers.go:424 — only allocatable / labels /
        taints / unschedulable / condition changes wake the queue."""
        return (old.allocatable != new.allocatable
                or old.labels != new.labels
                or old.taints != new.taints
                or old.unschedulable != new.unschedulable
                or old.conditions != new.conditions)

    def _delete_node(self, node: Node) -> None:
        self.cache.remove_node(node)

    # -- lifecycle -----------------------------------------------------------
    def sync(self) -> None:
        self.informers.sync_all()

    def pump(self) -> int:
        return self.informers.pump_all()

    # -- one cycle (reference: scheduleOne :438) ------------------------------
    def schedule_one(self, timeout: Optional[float] = 0.05) -> bool:
        """Pop + schedule + assume + bind one pod. Returns False when the
        queue stayed empty for `timeout`."""
        pod = self.queue.pop(timeout=timeout)
        if pod is None:
            return False
        if pod.deleted:
            return True
        cycle = self.queue.scheduling_cycle
        start = self.clock.now()
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        names = self.cache.node_tree.list_names()
        try:
            result = self._schedule(pod, names)
        except FitError as err:
            self.metrics.observe("unschedulable")
            if not self.disable_preemption:
                self._preempt(pod, err)
            self._record_failure(pod, cycle)
            return True
        except Exception:
            self.metrics.observe("error")
            self._record_failure(pod, cycle)
            raise
        assumed = pod.clone()
        assumed.node_name = result.suggested_host
        try:
            self.cache.assume_pod(assumed)
        except Exception:
            self.metrics.observe("error")
            self._record_failure(pod, cycle)
            return True
        self.queue.nominated.delete(pod)
        self._bind(assumed, result.suggested_host, pod, cycle)
        self.metrics.observe("scheduled")
        self.metrics.e2e_latency_sum += self.clock.now() - start
        return True

    def _schedule(self, pod: Pod, names: list[str]) -> ScheduleResult:
        if isinstance(self.algorithm, GenericScheduler):
            return self.algorithm.schedule(
                pod, self._snapshot.node_infos, names,
                priority_configs=self._priority_configs)
        return self.algorithm.schedule(pod, self._snapshot.node_infos, names)

    def _bind(self, assumed: Pod, host: str, orig: Pod, cycle: int) -> None:
        """Reference: the bind goroutine (scheduler.go:523) — store write +
        FinishBinding; on failure ForgetPod + re-queue."""
        try:
            self.store.bind_pod(assumed.key, host)
            self.cache.finish_binding(assumed)
            self.metrics.binding_count += 1
        except Exception:
            self.cache.forget_pod(assumed)
            self._record_failure(orig, cycle)

    def _record_failure(self, pod: Pod, cycle: int) -> None:
        """Reference: factory.go:643 MakeDefaultErrorFunc."""
        try:
            current = self.store.get(PODS, pod.key)
        except NotFoundError:
            self.queue.delete(pod)
            return
        if current.node_name:
            return
        self.queue.add_unschedulable_if_not_present(current, cycle)

    # -- preemption placeholder (full impl lands with the preemption kernels) --
    def _preempt(self, pod: Pod, err: FitError) -> None:
        self.metrics.preemption_attempts += 1

    # -- burst mode (TPU throughput path) -------------------------------------
    def schedule_burst(self, max_pods: int = 1024) -> int:
        """Drain up to max_pods from the queue and schedule them in one
        device launch (TPU algorithm only). Returns pods bound."""
        pods = []
        cycles = []
        while len(pods) < max_pods:
            pod = self.queue.pop(timeout=0.0)
            if pod is None:
                break
            if not pod.deleted:
                pods.append(pod)
                cycles.append(self.queue.scheduling_cycle)
        if not pods:
            return 0
        self._snapshot = self.cache.update_snapshot(self._snapshot)
        names = self.cache.node_tree.list_names()
        hosts = self.algorithm.schedule_burst(pods, self._snapshot.node_infos, names,
                                              bucket=max_pods)
        bound = 0
        for pod, host, cycle in zip(pods, hosts, cycles):
            if host is None:
                self.metrics.observe("unschedulable")
                self._record_failure(pod, cycle)
                continue
            assumed = pod.clone()
            assumed.node_name = host
            self.cache.assume_pod(assumed)
            self._bind(assumed, host, pod, cycle)
            self.metrics.observe("scheduled")
            bound += 1
        return bound

    def run(self, stop_after: Optional[Callable[[], bool]] = None) -> None:
        """wait.Until(scheduleOne, 0) analog; call from a thread."""
        while not self._stop.is_set():
            self.pump()
            self.schedule_one()
            if stop_after is not None and stop_after():
                return

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
