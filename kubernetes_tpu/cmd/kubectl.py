"""kubectl analog — the CLI user tool over the REST apiserver.

Mirrors the pkg/kubectl verbs the scheduler ecosystem exercises
(cmd/kubectl; cli-runtime): talks HTTP to the apiserver (never the store
directly — process boundary preserved), prints get tables and describe
blocks (with the object's Events), applies JSON manifests, deletes, and
runs the node maintenance verbs (cordon/uncordon/drain — drain honors
matching PodDisruptionBudgets like the eviction subresource; pass
--disable-eviction for the reference's unconditional-delete mode).

  kubectl-tpu --server URL get pods [-o json|wide] [--watch]
  kubectl-tpu get pods default/p0 | nodes n0
  kubectl-tpu describe pods default/p0
  kubectl-tpu create -f manifest.json      (one object or {"items": [...]})
  kubectl-tpu delete pods default/p0
  kubectl-tpu cordon n0 | uncordon n0 | drain n0
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any, Optional

DEFAULT_SERVER = "http://127.0.0.1:8001"


class APIError(SystemExit):
    pass


def _req(server: str, method: str, path: str, body: Optional[dict] = None,
         return_codes: tuple = ()):
    """HTTP round trip; server errors print the Status message and exit,
    except codes in `return_codes`, which return (code, status_dict) so
    callers can handle them (apply's AlreadyExists/Conflict races)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(server + path, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            status = json.loads(e.read() or b"{}")
            msg = status.get("message", str(e))
        except Exception:
            status, msg = {}, str(e)
        if e.code in return_codes:
            return (e.code, status)
        print(f"Error from server ({e.code}): {msg}", file=sys.stderr)
        raise APIError(1)


def _columns(kind: str, obj: dict) -> list[tuple[str, str]]:
    name = obj.get("name", "")
    ns = obj.get("namespace")
    cols = [("NAMESPACE", ns)] if ns else []
    cols.append(("NAME", name))
    if kind == "pods":
        phase = obj.get("phase", "")
        node = obj.get("node_name", "") or "<none>"
        cols += [("STATUS", phase), ("NODE", node),
                 ("PRIORITY", str(obj.get("priority", 0)))]
    elif kind == "nodes":
        ready = "Ready"
        for c in obj.get("conditions", []):
            if c.get("type") == "Ready" and c.get("status") != "True":
                ready = "NotReady"
        if obj.get("unschedulable"):
            ready += ",SchedulingDisabled"
        cols += [("STATUS", ready),
                 ("TAINTS", str(len(obj.get("taints", []))))]
    elif kind == "events":
        cols += [("TYPE", obj.get("type", "")),
                 ("REASON", obj.get("reason", "")),
                 ("OBJECT", obj.get("involved_key", "")),
                 ("COUNT", str(obj.get("count", 1))),
                 ("MESSAGE", obj.get("message", "")[:60])]
    elif kind == "poddisruptionbudgets":
        cols += [("MIN-AVAILABLE", str(obj.get("min_available"))),
                 ("ALLOWED-DISRUPTIONS",
                  str(obj.get("disruptions_allowed", 0)))]
    return cols


def _print_table(kind: str, objs: list[dict]) -> None:
    if not objs:
        print("No resources found.")
        return
    rows = [_columns(kind, o) for o in objs]
    headers = [h for h, _ in rows[0]]
    widths = [max(len(headers[i]), *(len(r[i][1]) for r in rows)) + 2
              for i in range(len(headers))]
    print("".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip())
    for r in rows:
        print("".join(v.ljust(w) for (_h, v), w in zip(r, widths)).rstrip())


def cmd_get(args) -> int:
    if args.name:
        obj = _req(args.server, "GET", f"/api/v1/{args.kind}/{args.name}")
        if args.output == "json":
            print(json.dumps(obj, indent=2))
        else:
            _print_table(args.kind, [obj])
        return 0
    if args.watch:
        import urllib.request as u
        with u.urlopen(f"{args.server}/api/v1/{args.kind}?watch=true") as resp:
            for raw in resp:
                line = raw.strip()
                if line:
                    ev = json.loads(line)
                    print(ev["type"], json.dumps(ev["object"]))
        return 0
    body = _req(args.server, "GET", f"/api/v1/{args.kind}")
    if args.output == "json":
        print(json.dumps(body, indent=2))
    else:
        _print_table(args.kind, body.get("items", []))
    return 0


def cmd_describe(args) -> int:
    obj = _req(args.server, "GET", f"/api/v1/{args.kind}/{args.name}")

    def walk(d: Any, indent: int = 0) -> None:
        pad = " " * indent
        if isinstance(d, dict):
            for k, v in d.items():
                if isinstance(v, (dict, list)) and v:
                    print(f"{pad}{k}:")
                    walk(v, indent + 2)
                else:
                    print(f"{pad}{k}: {v}")
        elif isinstance(d, list):
            for v in d:
                walk(v, indent)
        else:
            print(f"{pad}{d}")
    walk(obj)
    # events for the object, like kubectl describe's Events: block
    key = obj.get("namespace", "") and \
        f"{obj['namespace']}/{obj['name']}" or obj.get("name", "")
    evs = _req(args.server, "GET", "/api/v1/events").get("items", [])
    mine = [e for e in evs if e.get("involved_key") == key]
    if mine:
        print("events:")
        for e in mine:
            print(f"  {e['type']}\t{e['reason']}\tx{e.get('count', 1)}\t"
                  f"{e['message']}")
    return 0


def _load_items(args) -> list[tuple[str, dict]]:
    with open(args.filename) as f:
        manifest = json.load(f)
    items = manifest.get("items", [manifest]) \
        if isinstance(manifest, dict) else manifest
    out = []
    for item in items:
        kind = item.pop("kind", None) or getattr(args, "kind", None)
        if not kind:
            raise SystemExit("manifest item missing 'kind'")
        out.append((kind, item))
    return out


def cmd_create(args) -> int:
    for kind, item in _load_items(args):
        created = _req(args.server, "POST", f"/api/v1/{kind}", item)
        print(f"{kind}/{created.get('name', '?')} created")
    return 0


def cmd_apply(args) -> int:
    """Declarative create-or-update: POST, and on AlreadyExists re-read the
    live object and PUT the manifest over it at the current
    resourceVersion, retrying the read-modify-write on Conflict (kubectl
    apply's effective behavior for this model)."""
    from kubernetes_tpu.api.serde import CLUSTER_SCOPED_KINDS
    for kind, item in _load_items(args):
        r = _req(args.server, "POST", f"/api/v1/{kind}", item,
                 return_codes=(409,))
        if not (isinstance(r, tuple) and r[0] == 409):
            print(f"{kind}/{r.get('name', '?')} created")
            continue
        # exists: overlay at the live resourceVersion; a concurrent writer
        # between GET and PUT conflicts — re-read and retry, bounded
        name = item.get("name", "")
        key = name if kind in CLUSTER_SCOPED_KINDS \
            else f"{item.get('namespace', 'default')}/{name}"
        for _attempt in range(5):
            live = _req(args.server, "GET", f"/api/v1/{kind}/{key}")
            merged = {**live, **item,
                      "resource_version": live.get("resource_version", 0)}
            r = _req(args.server, "PUT", f"/api/v1/{kind}/{key}", merged,
                     return_codes=(409,))
            if not (isinstance(r, tuple) and r[0] == 409):
                break
        else:
            print(f"Error: {kind}/{key}: conflict persisted", file=sys.stderr)
            raise APIError(1)
        print(f"{kind}/{name} configured")
    return 0


def cmd_delete(args) -> int:
    _req(args.server, "DELETE", f"/api/v1/{args.kind}/{args.name}")
    print(f"{args.kind}/{args.name} deleted")
    return 0


def _patch_node(server: str, name: str, **fields) -> dict:
    node = _req(server, "GET", f"/api/v1/nodes/{name}")
    node.update(fields)
    return _req(server, "PUT", f"/api/v1/nodes/{name}", node)


def cmd_cordon(args) -> int:
    _patch_node(args.server, args.name, unschedulable=True)
    print(f"node/{args.name} cordoned")
    return 0


def cmd_uncordon(args) -> int:
    _patch_node(args.server, args.name, unschedulable=False)
    print(f"node/{args.name} uncordoned")
    return 0


def cmd_drain(args) -> int:
    """Cordon + evict every pod on the node THROUGH the eviction
    subresource (POST pods/{ns}/{name}/eviction): the server's atomic
    PDB check refuses with 429 + Retry-After when a pod's disruption
    budget is exhausted — the pod is left running and reported, exactly
    the reference drain behavior. --disable-eviction deletes directly
    (the reference flag that bypasses the eviction API)."""
    _patch_node(args.server, args.name, unschedulable=True)
    pods = _req(args.server, "GET", "/api/v1/pods").get("items", [])
    use_eviction = not getattr(args, "disable_eviction", False)
    refused = 0
    for p in pods:
        if p.get("node_name") != args.name:
            continue
        key = f"{p['namespace']}/{p['name']}"
        if use_eviction:
            out = _req(args.server, "POST",
                       f"/api/v1/pods/{key}/eviction", {},
                       return_codes=(429,))
            if isinstance(out, tuple):   # (429, status): budget exhausted
                print(f"error when evicting pod {key}: "
                      f"{out[1].get('message', 'disruption budget')}",
                      file=sys.stderr)
                refused += 1
                continue
        else:
            _req(args.server, "DELETE", f"/api/v1/pods/{key}")
        print(f"pod/{key} evicted")
    print(f"node/{args.name} drained" + (f" ({refused} refused)" if refused else ""))
    return 1 if refused else 0


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="kubectl-tpu")
    ap.add_argument("--server", "-s", default=DEFAULT_SERVER)
    sub = ap.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("-o", "--output", choices=["table", "wide", "json"],
                   default="table")
    g.add_argument("-w", "--watch", action="store_true")
    g.set_defaults(fn=cmd_get)

    d = sub.add_parser("describe")
    d.add_argument("kind")
    d.add_argument("name")
    d.set_defaults(fn=cmd_describe)

    c = sub.add_parser("create")
    c.add_argument("-f", "--filename", required=True)
    c.add_argument("--kind")
    c.set_defaults(fn=cmd_create)

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)
    a.add_argument("--kind")
    a.set_defaults(fn=cmd_apply)

    rm = sub.add_parser("delete")
    rm.add_argument("kind")
    rm.add_argument("name")
    rm.set_defaults(fn=cmd_delete)

    for verb, fn in (("cordon", cmd_cordon), ("uncordon", cmd_uncordon),
                     ("drain", cmd_drain)):
        p = sub.add_parser(verb)
        p.add_argument("name")
        if verb == "drain":
            p.add_argument("--disable-eviction", action="store_true",
                           help="delete pods directly, skipping the PDB "
                                "eviction check")
        p.set_defaults(fn=fn)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
