"""Scheduler command — cmd/kube-scheduler analog.

Mirrors cmd/kube-scheduler/app/server.go: flag/config layering
(options → SchedulerConfiguration → algorithm source), optional leader
election (:248-263), healthz (:201) and /metrics (:284) endpoints, then the
scheduling loop. The cluster substrate is the in-process store, loaded from
a cluster-spec JSON (hollow nodes + pods) or left empty for API-driven use.

Run: python -m kubernetes_tpu.cmd.scheduler --cluster-spec spec.json --once
"""
from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kubernetes_tpu.apis.config import (
    SchedulerConfiguration, AlgorithmSource,
)
from kubernetes_tpu.factory import create_scheduler
from kubernetes_tpu.metrics import render_metrics, reset_metrics
from kubernetes_tpu.models.hollow import NodeStrategy, PodStrategy, populate_store, make_pods
from kubernetes_tpu.store.store import Store, PODS
from kubernetes_tpu.utils.leader_election import LeaderElector, LeaderElectionConfig


def build_config(args) -> SchedulerConfiguration:
    if args.config:
        cfg = SchedulerConfiguration.from_file(args.config)
    else:
        cfg = SchedulerConfiguration()
    if args.algorithm_provider:
        cfg.algorithm_source = AlgorithmSource(provider=args.algorithm_provider)
    if args.policy_config_file:
        cfg.algorithm_source = AlgorithmSource(
            provider=None, policy_file=args.policy_config_file)
    if args.scheduler_name:
        cfg.scheduler_name = args.scheduler_name
    if args.percentage_of_nodes_to_score is not None:
        cfg.percentage_of_nodes_to_score = args.percentage_of_nodes_to_score
    if args.disable_preemption:
        cfg.disable_preemption = True
    if args.leader_elect:
        cfg.leader_election.leader_elect = True
    if args.feature_gates:
        for item in args.feature_gates.split(","):
            key, _, value = item.partition("=")
            cfg.feature_gates[key.strip()] = value.strip().lower() != "false"
    return cfg


def load_cluster_spec(store: Store, path: str) -> None:
    """Cluster-spec JSON: {"nodes": [NodeStrategy kwargs...],
    "existing_pods": [PodStrategy kwargs...], "pending_pods": [...]}"""
    with open(path) as f:
        spec = json.load(f)
    node_strategies = [NodeStrategy(**n) for n in spec.get("nodes", [])]
    existing = [PodStrategy(**p) for p in spec.get("existing_pods", [])]
    populate_store(store, node_strategies, existing)
    idx = 0
    for p in spec.get("pending_pods", []):
        st = PodStrategy(**p)
        for pod in make_pods(st, start_index=idx):
            store.create(PODS, pod)
        idx += st.count


class _Handler(BaseHTTPRequestHandler):
    scheduler = None

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, body: str, ctype: str = "text/plain"):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        from urllib.parse import parse_qs, urlparse
        u = urlparse(self.path)
        if u.path == "/healthz":
            self._send(200, "ok")
        elif u.path == "/metrics":
            # scheduler families + the process-global registry (device
            # pipeline, informers, workqueues) in one scrape — name sets
            # are disjoint, so the concatenation stays lintable
            from kubernetes_tpu import obs
            self._send(200, render_metrics(self.scheduler)
                       + obs.render_global(),
                       "text/plain; version=0.0.4")
        elif u.path == "/debug/traces":
            # same query knobs as the apiserver route: ?limit= newest N,
            # ?cat= host|device
            from kubernetes_tpu.obs import trace as obs_trace
            q = parse_qs(u.query)
            limit = q.get("limit", [None])[0]
            if limit is not None:
                try:
                    limit = int(limit)
                    if limit < 0:
                        raise ValueError(limit)
                except ValueError:
                    self._send(400, f"invalid limit {limit!r}")
                    return
            cat = q.get("cat", [None])[0]
            self._send(200, json.dumps(obs_trace.to_chrome(limit=limit,
                                                           cat=cat)),
                       "application/json")
        elif u.path == "/debug/timeseries":
            # the in-process time-series ring — same query knobs as the
            # apiserver route: ?family= one family, ?window= newest N
            from kubernetes_tpu.obs import timeseries as obs_timeseries
            q = parse_qs(u.query)
            window = q.get("window", [None])[0]
            if window is not None:
                try:
                    window = int(window)
                    if window < 0:
                        raise ValueError(window)
                except ValueError:
                    self._send(400, f"invalid window {window!r}")
                    return
            family = q.get("family", [None])[0]
            self._send(200, json.dumps(obs_timeseries.SCRAPER.series(
                family=family, window=window)), "application/json")
        elif u.path == "/debug/sched":
            from kubernetes_tpu import obs
            snap = obs.debug_snapshot()
            # this command OWNS a scheduler: serve its sections directly
            # (no dependence on registration order / instance races)
            snap["scheduler"] = self.scheduler.debug_state()
            self._send(200, json.dumps(snap), "application/json")
        elif u.path == "/configz":
            self._send(200, json.dumps(self.scheduler_config.to_dict()),
                       "application/json")
        else:
            self._send(404, "not found")

    def do_DELETE(self):
        if self.path == "/metrics":
            reset_metrics(self.scheduler)
            self._send(200, "reset")
        else:
            self._send(404, "not found")


def serve_http(sched, cfg, port: int) -> ThreadingHTTPServer:
    handler = type("Handler", (_Handler,), {
        "scheduler": sched, "scheduler_config": cfg})
    server = ThreadingHTTPServer(("127.0.0.1", port), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="kube-scheduler-tpu")
    ap.add_argument("--config", help="SchedulerConfiguration JSON file")
    ap.add_argument("--algorithm-provider")
    ap.add_argument("--policy-config-file")
    ap.add_argument("--scheduler-name")
    ap.add_argument("--percentage-of-nodes-to-score", type=int)
    ap.add_argument("--disable-preemption", action="store_true")
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--leader-elect-identity", default="scheduler-0")
    ap.add_argument("--feature-gates", help="k=v,k2=v2 (e.g. TPUScoring=false)")
    ap.add_argument("--cluster-spec", help="cluster-spec JSON to load")
    ap.add_argument("--port", type=int, default=0, help="healthz/metrics port")
    ap.add_argument("--once", action="store_true",
                    help="drain the queue once and exit (bench/CI mode)")
    ap.add_argument("--burst", type=int, default=0)
    ap.add_argument("--profile-dir",
                    help="write a jax.profiler trace (kernel timelines, "
                         "transfers) covering the scheduling loop — the "
                         "EnableProfiling/pprof analog (server.go:301)")
    ap.add_argument("--api-port", type=int, default=0,
                    help="also serve the REST apiserver surface over this "
                         "process's store (the in-process master of "
                         "test/integration/util/util.go:42) — kubectl-tpu "
                         "points at it")
    ap.add_argument("--server",
                    help="attach to a REMOTE apiserver URL instead of an "
                         "embedded store: list+watch over HTTP with "
                         "resourceVersion resume and 410 re-list "
                         "(reflector.go:159) — the out-of-process posture "
                         "of every reference control-plane component")
    ap.add_argument("--token",
                    help="bearer token for --server (tokenfile authn; the "
                         "bootstrapped scheduler identity is "
                         "system:kube-scheduler)")
    args = ap.parse_args(argv)

    cfg = build_config(args)
    if args.server:
        if args.api_port:
            raise SystemExit("--server and --api-port are exclusive: a "
                             "remote-attached scheduler has no store of its "
                             "own to serve")
        from kubernetes_tpu.store.remote import RemoteStore
        store = RemoteStore(args.server, token=args.token)
        if args.cluster_spec:
            raise SystemExit("--cluster-spec requires the embedded store; "
                             "create objects through the apiserver instead")
    else:
        store = Store(watch_log_size=1 << 20)
        if args.cluster_spec:
            load_cluster_spec(store, args.cluster_spec)
    sched = create_scheduler(store, cfg)
    sched.sync()
    server = serve_http(sched, cfg, args.port) if args.port else None
    api_server = None
    if args.api_port:
        from kubernetes_tpu.apiserver.server import APIServer
        api_server = APIServer(store, port=args.api_port).start()
    profiler = None
    if args.profile_dir:
        from kubernetes_tpu.utils.tracing import Profiler
        profiler = Profiler(args.profile_dir)
        profiler.start()

    def run_loop():
        sched.pump()
        if args.once:
            while (sched.schedule_burst(max_pods=args.burst)
                   if args.burst else sched.schedule_one(timeout=0.0)):
                pass
            sched.pump()
        else:
            sched.run()

    if cfg.leader_election.leader_elect:
        # the scheduling loop runs on its own thread so the elector keeps
        # renewing the lease (client-go runs OnStartedLeading in a goroutine
        # while the renew loop continues — otherwise a blocked winner stops
        # renewing and a second instance goes active: split-brain)
        loop_done = threading.Event()

        def start_leading():
            def wrapped():
                try:
                    run_loop()
                finally:
                    loop_done.set()
            threading.Thread(target=wrapped, daemon=True).start()

        elector = LeaderElector(store, LeaderElectionConfig(
            lock_name=cfg.leader_election.lock_object_name,
            identity=args.leader_elect_identity,
            lease_duration=cfg.leader_election.lease_duration,
            renew_deadline=cfg.leader_election.renew_deadline,
            retry_period=cfg.leader_election.retry_period,
            on_started_leading=start_leading,
            on_stopped_leading=lambda: sched.stop()))
        while not loop_done.is_set():
            elector.step()
            if loop_done.wait(cfg.leader_election.retry_period):
                break
        elector.release()
    else:
        run_loop()

    if profiler is not None:
        profiler.stop()
    if args.once:
        attempts = sched.metrics.schedule_attempts
        print(json.dumps({"scheduled": attempts["scheduled"],
                          "unschedulable": attempts["unschedulable"],
                          "errors": attempts["error"]}))
    if server:
        server.shutdown()
    if api_server is not None:
        api_server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
