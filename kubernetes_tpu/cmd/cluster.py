"""Cluster bootstrap — the kubeadm analog.

`kubeadm init` assembles a control plane from static manifests
(cmd/kubeadm); this assembles the in-process equivalent over one store and
runs it: REST apiserver (+admission), the TPU scheduler loop, the
controller manager (disruption / node-lifecycle / podgc / replicaset), and
a fleet of hollow kubelets heartbeating leases, node readiness, and pod
lifecycle (the kubemark cluster of test/kubemark/). The result is a
cluster-in-a-process that kubectl-tpu can drive end to end:

    python -m kubernetes_tpu.cmd.cluster --nodes 100 --api-port 8001
    kubectl-tpu -s http://127.0.0.1:8001 create -f rs.json
    kubectl-tpu -s http://127.0.0.1:8001 get pods

Also usable in-process (tests, harnesses) via `Cluster`.
"""
from __future__ import annotations

import argparse
import threading
import time
from typing import Optional

from kubernetes_tpu.api.types import Node
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.models.hollow import (
    NodeStrategy, make_hollow_nodes, HollowKubelet,
)
from kubernetes_tpu.store.store import Store, NODES, PODS
from kubernetes_tpu.scheduler import Scheduler


class Cluster:
    """All control-plane components over one store."""

    def __init__(self, n_nodes: int = 10, zones: int = 3,
                 api_port: int = 0, use_tpu: bool = True,
                 kubelet_interval: float = 1.0):
        self.store = Store(watch_log_size=max(1 << 16, 8 * n_nodes))
        for node in make_hollow_nodes(NodeStrategy(count=n_nodes,
                                                   zones=zones)):
            self.store.create(NODES, node)
        self.api = APIServer(self.store, port=api_port) if api_port >= 0 \
            else None
        self.scheduler = Scheduler(self.store, use_tpu=use_tpu,
                                   percentage_of_nodes_to_score=100)
        self.controllers = ControllerManager(self.store)
        self.kubelets = [HollowKubelet(self.store, node.name)
                         for node in self.store.list(NODES)[0]]
        # one virtual proxier per node (kube-proxy at kubemark fidelity:
        # HollowProxy) — endpoints propagate into per-node forwarding tables
        from kubernetes_tpu.proxy.proxier import VirtualProxier
        self.proxies = [VirtualProxier(self.store, node.name)
                        for node in self.store.list(NODES)[0]]
        self.kubelet_interval = kubelet_interval
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Cluster":
        if self.api is not None:
            self.api.start()
        self.scheduler.sync()
        self.controllers.sync()
        for p in self.proxies:
            p.sync()
        self.kubelet_tick()

        def sched_loop():
            while not self._stop.is_set():
                self.scheduler.pump()
                if not self.scheduler.schedule_burst(max_pods=1024):
                    self._stop.wait(0.02)

        def controller_loop():
            while not self._stop.is_set():
                self.controllers.pump()
                self._stop.wait(0.05)

        def kubelet_loop():
            while not self._stop.is_set():
                self.kubelet_tick()
                self._stop.wait(self.kubelet_interval)

        def proxy_loop():
            while not self._stop.is_set():
                for p in self.proxies:
                    p.pump()
                self._stop.wait(0.05)

        for fn in (sched_loop, controller_loop, kubelet_loop, proxy_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def kubelet_tick(self) -> None:
        # one list serves the whole fleet (see HollowKubelet.heartbeat)
        pods, _rv = self.store.list(PODS)
        by_node: dict[str, list] = {}
        for p in pods:
            if p.node_name:
                by_node.setdefault(p.node_name, []).append(p)
        for k in self.kubelets:
            k.heartbeat(pods=by_node.get(k.node_name, ()))

    def stop(self) -> None:
        self._stop.set()
        self.scheduler.stop()
        for t in self._threads:
            t.join(2.0)
        if self.api is not None:
            self.api.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- conveniences --------------------------------------------------------
    @property
    def url(self) -> Optional[str]:
        return self.api.url if self.api is not None else None

    def wait_for(self, predicate, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.05)
        return False


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="kubeadm-tpu",
                                 description="cluster-in-a-process bootstrap")
    ap.add_argument("--nodes", type=int, default=10)
    ap.add_argument("--zones", type=int, default=3)
    ap.add_argument("--api-port", type=int, default=8001)
    ap.add_argument("--no-tpu", action="store_true")
    args = ap.parse_args(argv)
    cluster = Cluster(n_nodes=args.nodes, zones=args.zones,
                      api_port=args.api_port, use_tpu=not args.no_tpu)
    cluster.start()
    print(f"control plane up: {cluster.url} "
          f"({args.nodes} hollow nodes, scheduler + controllers + kubelets)")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
