"""REST API server over the store — the kube-apiserver surface.

The reference's apiserver is REST + watch over etcd
(staging/src/k8s.io/apiserver; pkg/master installs core/v1 at /api/v1).
This serves the same contract over the in-memory store:

  GET    /healthz | /readyz | /version
  GET    /api/v1/{kind}                 -> {"kind","items","resourceVersion"}
  GET    /api/v1/{kind}?watch=true&resourceVersion=N
                                        -> chunked JSON-lines event stream
  GET    /api/v1/{kind}/{key...}        -> object
  POST   /api/v1/{kind}                 -> admission chain -> create (201)
  PUT    /api/v1/{kind}/{key...}        -> update (409 on rv conflict)
  DELETE /api/v1/{kind}/{key...}        -> deleted object
  POST   /api/v1/pods/{ns}/{name}/binding  {"node": "..."}
                                        -> bind (the scheduler's write verb,
                                           factory.go:710)

Namespaced kinds key as {namespace}/{name}; cluster-scoped (nodes, PVs,
priorityclasses) as {name}. Watch streams resume from resourceVersion and
end with a 410-Gone error line when the log window expired (the client
re-lists, exactly like the reference's Reflector).
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs

from kubernetes_tpu import obs
from kubernetes_tpu.obs import trace as obs_trace
from kubernetes_tpu.obs import timeseries as obs_timeseries
from kubernetes_tpu.api import serde
from kubernetes_tpu.apiserver.admission import AdmissionChain, AdmissionError
from kubernetes_tpu.apiserver.auth import Attributes
from kubernetes_tpu.store.store import (
    Store, PODS, PODGROUPS, AlreadyExistsError, BackpressureError,
    ConflictError, DisruptionBudgetError, FencedError, NotFoundError,
    ExpiredError,
)

API_PREFIX = "/api/v1"


def wire_line(etype: str, obj, rv: int) -> bytes:
    """The watch stream's wire encoding of one event — THE byte-ring
    contract: installed into the store as the serialize-once encoder
    (each event is encoded once per subscription class and every
    classmate's HTTP stream serves the identical bytes). One JSON object
    per line, newline-terminated; chunked framing rides on top."""
    return json.dumps({"type": etype, "resourceVersion": rv,
                       "object": serde.to_dict(obj)}).encode() + b"\n"

# request metrics (apiserver_request_total / ..._duration_seconds /
# ..._longrunning analogs, staging/src/k8s.io/apiserver metrics.go) —
# registered at import so /metrics always exposes the families
REQUEST_TOTAL = obs.counter(
    "apiserver_request_total",
    "Requests served, by verb, resource, and HTTP code.",
    ("verb", "resource", "code"))
REQUEST_DURATION = obs.histogram(
    "apiserver_request_duration_seconds",
    "Request latency by verb (long-running watch streams excluded).",
    ("verb",))
IN_FLIGHT = obs.gauge(
    "apiserver_requests_in_flight",
    "Requests currently being served.")
ACTIVE_WATCHES = obs.gauge(
    "apiserver_active_watches",
    "Currently open watch streams, by resource.", ("resource",))


def make_handler(store: Store, admission: AdmissionChain,
                 authenticator=None, authorizer=None):
    # serialize-once byte ring: the store's commit core encodes each watch
    # event ONCE per subscription class with this server's wire encoder;
    # _watch then streams the shared bytes (zero per-watcher encoding)
    if hasattr(store, "set_wire_encoder"):
        store.set_wire_encoder(wire_line)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):   # quiet
            pass

        # -- instrumentation ------------------------------------------------
        def send_response(self, code, message=None):
            self._last_code = code
            super().send_response(code, message)

        def _classify(self) -> tuple[str, str]:
            """(verb, resource) for the request-metric labels — REST verbs
            for API paths, the raw method for operational endpoints."""
            u = urlparse(self.path)
            parts = [p for p in u.path.split("/") if p]
            method = self.command
            if len(parts) >= 3 and "/".join(parts[:2]) == "api/v1":
                resource = parts[2]
                if method == "GET":
                    if len(parts) == 3:
                        q = parse_qs(u.query)
                        verb = ("watch"
                                if q.get("watch", ["false"])[0] == "true"
                                else "list")
                    else:
                        verb = "get"
                else:
                    verb = {"POST": "create", "PUT": "update",
                            "DELETE": "delete"}.get(method, method.lower())
                return verb, resource
            return method.lower(), (parts[0] if parts else "/")

        def _instrumented(self, inner) -> None:
            verb, resource = self._classify()
            self._last_code = 0
            t0 = time.perf_counter()
            IN_FLIGHT.inc()
            try:
                inner()
            finally:
                IN_FLIGHT.dec()
                REQUEST_TOTAL.labels(verb, resource,
                                     str(self._last_code or 0)).inc()
                # long-running requests skip the duration histogram (the
                # reference excludes watches the same way) — a watch's
                # lifetime would swamp the latency signal
                if verb != "watch":
                    REQUEST_DURATION.labels(verb).observe(
                        time.perf_counter() - t0)

        def do_GET(self):
            self._instrumented(self._serve_GET)

        def do_POST(self):
            self._instrumented(self._serve_POST)

        def do_PUT(self):
            self._instrumented(self._serve_PUT)

        def do_DELETE(self):
            self._instrumented(self._serve_DELETE)

        # -- authn/authz ----------------------------------------------------
        def _authenticate(self):
            """Bearer-token authn (tokenfile analog). Returns the UserInfo
            (None when auth is disabled — the open in-process posture)."""
            if authenticator is None:
                return None
            return authenticator.authenticate(
                self.headers.get("Authorization"))

        def _authorized(self, user, verb: str, resource: str,
                        name: str = "") -> bool:
            """401 for anonymous, 403 on authorizer deny; True = proceed.
            With auth disabled every request passes (trusted in-process
            callers)."""
            if authenticator is None:
                return True
            if user is None:
                self._error(401, "Unauthorized",
                            "authentication required: present a bearer "
                            "token")
                return False
            if authorizer is not None and not authorizer.authorize(
                    Attributes(user=user, verb=verb, resource=resource,
                               name=name)):
                self._error(403, "Forbidden",
                            f"user {user.name!r} cannot {verb} {resource}"
                            f"{'/' + name if name else ''}")
                return False
            return True

        def _user_name(self, user) -> str | None:
            """The identity admission plugins act on: the VERIFIED token
            identity when auth is enabled, else the (trusting, in-process)
            X-Remote-User header — the spoofable header is dead the moment
            an authenticator is configured."""
            if authenticator is not None:
                return user.name if user is not None else None
            return self.headers.get("X-Remote-User")

        # -- helpers --------------------------------------------------------
        def _send(self, code: int, payload, chunked: bool = False,
                  headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, reason: str, message: str,
                   headers: dict | None = None,
                   extra: dict | None = None) -> None:
            body = {"kind": "Status", "status": "Failure",
                    "reason": reason, "message": message, "code": code}
            if extra:
                body.update(extra)
            self._send(code, body, headers=headers)

        def _route(self):
            u = urlparse(self.path)
            q = parse_qs(u.query)
            parts = [p for p in u.path.split("/") if p]
            return u.path, parts, q

        def _body(self):
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        # -- verbs ----------------------------------------------------------
        def _serve_GET(self):
            path, parts, q = self._route()
            if path in ("/healthz", "/readyz", "/livez"):
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")
                return
            if path == "/metrics":
                # one scrape of the process-global registry: apiserver
                # request families plus whatever components (workqueues,
                # informers, device pipeline) registered in this process
                body = obs.render_global().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/debug/traces":
                # Chrome trace-event JSON of the span ring buffer —
                # loadable in Perfetto / chrome://tracing. `?limit=N`
                # keeps the newest N spans, `?cat=host|device` filters by
                # category (the full 64k-span ring is a multi-MB response)
                limit = q.get("limit", [None])[0]
                if limit is not None:
                    try:
                        limit = int(limit)
                        if limit < 0:
                            raise ValueError(limit)
                    except ValueError:
                        self._error(400, "BadRequest",
                                    f"invalid limit {limit!r}")
                        return
                cat = q.get("cat", [None])[0]
                self._send(200, obs_trace.to_chrome(limit=limit, cat=cat))
                return
            if path == "/debug/sched":
                # deep scheduler introspection: every registered debug
                # section (queue depths, parked gangs, device mirror,
                # victim table, ledger) plus THIS server's store (rv,
                # object counts, per-watcher cursor lag)
                snap = obs.debug_snapshot()
                snap["store"] = store.debug_state()
                self._send(200, snap)
                return
            if path == "/debug/timeseries":
                # the in-process time-series ring (obs.timeseries.SCRAPER):
                # `?family=NAME` filters to one family, `?window=N` keeps
                # the newest N samples. Empty (samples: 0) until a bench
                # cell or operator starts the scraper.
                window = q.get("window", [None])[0]
                if window is not None:
                    try:
                        window = int(window)
                        if window < 0:
                            raise ValueError(window)
                    except ValueError:
                        self._error(400, "BadRequest",
                                    f"invalid window {window!r}")
                        return
                family = q.get("family", [None])[0]
                self._send(200, obs_timeseries.SCRAPER.series(
                    family=family, window=window))
                return
            if path == "/version":
                self._send(200, {"gitVersion": "v0.3.0-kubernetes-tpu"})
                return
            if len(parts) < 3 or "/".join(parts[:2]) != "api/v1":
                self._error(404, "NotFound", path)
                return
            kind = parts[2]
            if kind not in serde.KIND_TYPES:
                self._error(404, "NotFound", f"unknown resource {kind}")
                return
            user = self._authenticate()
            if len(parts) == 3:
                if q.get("watch", ["false"])[0] == "true":
                    if not self._authorized(user, "watch", kind):
                        return
                    self._watch(kind, q)
                    return
                if not self._authorized(user, "list", kind):
                    return
                objs, rv = store.list(kind)
                self._send(200, {"kind": kind, "resourceVersion": rv,
                                 "items": [serde.to_dict(o) for o in objs]})
                return
            key = "/".join(parts[3:])
            if not self._authorized(user, "get", kind, key):
                return
            try:
                self._send(200, serde.to_dict(store.get(kind, key)))
            except NotFoundError:
                self._error(404, "NotFound", f"{kind}/{key}")

        def _watch(self, kind: str, q) -> None:
            since = q.get("resourceVersion", [None])[0]
            # opaque subscription-class key: watchers passing the same
            # (kind, selector) share one materialize-once / encode-once
            # class in the commit core (NOT a server-side event filter)
            selector = q.get("selector", [None])[0]
            try:
                w = store.watch(kind,
                                int(since) if since is not None else None,
                                selector=selector)
            except ExpiredError as e:
                self._error(410, "Expired", str(e))
                return
            # pre-encoded wire bytes straight from the class ring when the
            # core has the byte-ring verbs (a stale prebuilt .so degrades
            # to per-stream encoding)
            use_bytes = hasattr(getattr(store, "_core", None), "poll_bytes")
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            ACTIVE_WATCHES.labels(kind).inc()

            def emit(line: bytes) -> bool:
                try:
                    self.wfile.write(hex(len(line))[2:].encode() + b"\r\n"
                                     + line + b"\r\n")
                    self.wfile.flush()
                    return True
                except (BrokenPipeError, ConnectionResetError, OSError):
                    return False
            try:
                while True:
                    try:
                        if use_bytes:
                            line = w.next_bytes(timeout=0.5)
                        else:
                            ev = w.next(timeout=0.5)
                            line = None if ev is None else wire_line(
                                ev.type, ev.obj, ev.resource_version)
                    except ExpiredError:
                        # this consumer fell behind the fan-out ring and
                        # was dropped-with-resync: end the stream — the
                        # client reconnects from its last seen rv and gets
                        # a replay, or a 410 -> re-list (reflector contract)
                        break
                    if line is None:
                        # blank-line keep-alive (an empty chunk would be the
                        # stream terminator); readers skip empty lines
                        if not emit(b"\n"):
                            break
                        continue
                    if not emit(line):
                        break
            finally:
                ACTIVE_WATCHES.labels(kind).dec()
                w.stop()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
                self.close_connection = True

        def _serve_POST(self):
            path, parts, q = self._route()
            user = self._authenticate()
            # binding subresource: POST /api/v1/pods/{ns}/{name}/binding
            if len(parts) == 6 and parts[2] == PODS and parts[5] == "binding":
                key = f"{parts[3]}/{parts[4]}"
                if not self._authorized(user, "create", PODS, key):
                    return
                body = self._body()
                node = body.get("node", "")
                # optional fleet fencing token(s): [[scope, token], ...]
                fence = [(str(s), int(t)) for s, t in body.get("fence") or []]
                try:
                    current = store.get(PODS, key)
                    # the binding subresource runs admission too
                    # (NodeRestriction: node identities never bind)
                    admission.admit_binding(current, node, store,
                                            user=self._user_name(user))
                    if fence:
                        store.bind_pod(key, node, fence=fence)
                    else:
                        store.bind_pod(key, node)
                except AdmissionError as e:
                    self._error(422, "Invalid", str(e))
                    return
                except FencedError as e:
                    # superseded partition-lease token: the whole write
                    # was rejected atomically (reason distinguishes it
                    # from the rv-CAS loss on the wire)
                    self._error(409, "Fenced", str(e))
                    return
                except ConflictError as e:
                    # rv-CAS bind loss: the pod is already bound — the
                    # racing loser re-queues, never overwrites
                    self._error(409, "Conflict", str(e))
                    return
                except NotFoundError:
                    self._error(404, "NotFound", key)
                    return
                self._send(201, {"kind": "Status", "status": "Success"})
                return
            # fence-advance verb: POST /api/v1/fences/{scope} {"token": N}
            # — the claim protocol's handoff write (a new partition-lease
            # holder fences out the superseded one BEFORE replaying)
            if len(parts) == 4 and parts[2] == "fences":
                scope = parts[3]
                if not self._authorized(user, "update", "fences", scope):
                    return
                try:
                    token = int(self._body().get("token"))
                except (TypeError, ValueError) as e:
                    self._error(400, "BadRequest", f"token: {e}")
                    return
                if not store.advance_fence(scope, token):
                    self._error(409, "Fenced",
                                f"fence {scope!r}: token {token} is "
                                f"already superseded")
                    return
                self._send(200, {"kind": "Status", "status": "Success",
                                 "scope": scope, "token": token})
                return
            # batched eviction (round 23): POST /api/v1/pods/evictions
            # {"keys": [...], "reason"?, "stop_on_refusal"?} — the churn
            # plane's one-call PDB-guarded delete. Per-item outcomes come
            # back in the body (evicted/refused/missing/skipped/invalid);
            # a refusal is an OUTCOME here, never a 429 — the whole batch
            # always answers, callers refund tokens per "refused" item.
            if len(parts) == 4 and parts[2] == PODS \
                    and parts[3] == "evictions":
                if not self._authorized(user, "create", PODS):
                    return
                body = self._body()
                keys = list(body.get("keys") or [])
                outcomes: dict = {}
                attempt: list = []
                for key in keys:
                    try:
                        admission.admit_delete(
                            PODS, store.get(PODS, key), store,
                            user=self._user_name(user))
                    except AdmissionError:
                        outcomes[key] = "invalid"
                        continue
                    except NotFoundError:
                        outcomes[key] = "missing"
                        continue
                    attempt.append(key)
                if attempt:
                    outcomes.update(store.evict_many(
                        attempt, reason=body.get("reason", "api"),
                        stop_on_refusal=bool(body.get("stop_on_refusal"))))
                self._send(200, {"kind": "Status", "status": "Success",
                                 "outcomes": outcomes})
                return
            # eviction subresource: POST /api/v1/pods/{ns}/{name}/eviction
            # — PDB-guarded delete (reference: registry/core/pod/rest/
            # eviction.go). An exhausted budget answers 429 TooManyRequests
            # with Retry-After; the caller backs off and retries, like the
            # reference's EvictionsRetry contract.
            if len(parts) == 6 and parts[2] == PODS \
                    and parts[5] == "eviction":
                key = f"{parts[3]}/{parts[4]}"
                if not self._authorized(user, "create", PODS, key):
                    return
                # delete admission runs first (NodeRestriction: a kubelet
                # may evict only pods bound to its own node)
                try:
                    admission.admit_delete(PODS, store.get(PODS, key),
                                           store,
                                           user=self._user_name(user))
                    gone = store.evict_pod(key, reason="api")
                except AdmissionError as e:
                    self._error(422, "Invalid", str(e))
                    return
                except DisruptionBudgetError as e:
                    self._error(
                        429, "TooManyRequests", str(e),
                        headers={"Retry-After":
                                 str(int(e.retry_after))})
                    return
                except NotFoundError:
                    self._error(404, "NotFound", key)
                    return
                self._send(201, serde.to_dict(gone))
                return
            if len(parts) != 3 or parts[2] not in serde.KIND_TYPES:
                self._error(404, "NotFound", path)
                return
            kind = parts[2]
            if not self._authorized(user, "create", kind):
                return
            body = self._body()
            if isinstance(body, dict) and "items" in body:
                # collection create (round 17): the serving lane's batched
                # arrival ingest — one admission-gate evaluation and one
                # ledger admission batch land server-side in create_many
                self._create_collection(kind, body["items"], user)
                return
            admitted = None
            try:
                obj = serde.from_dict(kind, body)
                obj = admitted = admission.admit(
                    kind, obj, store, user=self._user_name(user))
                created = store.create(kind, obj)
            except AdmissionError as e:
                self._error(422, "Invalid", str(e))
                return
            except BackpressureError as e:
                # serving load shed (store.admission_gate): the write
                # never landed, so the client may safely retry after the
                # suggested backoff. Reason "Backpressure" distinguishes
                # this 429 from the eviction subresource's budget refusal
                # on the wire (RemoteStore maps them to distinct errors).
                # The admitted chain's side effects roll back like any
                # refused write (quota charges must not leak per shed).
                admission.refund(kind, admitted, store)
                self._error(429, "Backpressure", str(e),
                            headers={"Retry-After":
                                     f"{e.retry_after:.3f}"})
                return
            except AlreadyExistsError as e:
                # the admitted write never landed: roll back side-effecting
                # admissions (quota usage) or the charge leaks per retry
                admission.refund(kind, admitted, store)
                self._error(409, "AlreadyExists", str(e))
                return
            except (TypeError, ValueError, KeyError) as e:
                self._error(400, "BadRequest", str(e))
                return
            self._send(201, serde.to_dict(created))

        def _create_collection(self, kind, items, user) -> None:
            """Batched create: every item rides the admission chain, then
            ONE store.create_many (one gate evaluation + one ledger
            admission batch for pods). A partial shed answers 429
            reason=Backpressure with `accepted` in the status body (the
            first `accepted` items landed) + Retry-After — shed items'
            admission side effects (quota charges) are refunded, landed
            ones are not."""
            admitted: list = []
            try:
                for d in items:
                    obj = serde.from_dict(kind, d)
                    admitted.append(admission.admit(
                        kind, obj, store, user=self._user_name(user)))
            except AdmissionError as e:
                for a in admitted:
                    admission.refund(kind, a, store)
                self._error(422, "Invalid", str(e))
                return
            except (TypeError, ValueError, KeyError) as e:
                for a in admitted:
                    admission.refund(kind, a, store)
                self._error(400, "BadRequest", str(e))
                return
            try:
                stored = store.create_many(kind, admitted)
            except BackpressureError as e:
                k = max(0, min(int(getattr(e, "accepted", 0)),
                               len(admitted)))
                for a in admitted[k:]:
                    admission.refund(kind, a, store)
                self._error(429, "Backpressure", str(e),
                            headers={"Retry-After": f"{e.retry_after:.3f}"},
                            extra={"accepted": k})
                return
            except AlreadyExistsError as e:
                # callers pass fresh uniquely-named objects (create_many
                # contract); a duplicate is a caller bug, answered like
                # the single-create path
                self._error(409, "AlreadyExists", str(e))
                return
            self._send(201, {"kind": "Status", "status": "Success",
                             "created": len(stored or admitted)})

        def _update_collection(self, kind, body, user) -> None:
            """Batched update (round 23): every item rides the update
            admission chain against its current stored object, then ONE
            `store.update_many` (rv-CAS per item: resource_version 0/absent
            skips the CAS, anything else must match). The response carries
            per-item refusals — `conflicts` and `missing` key lists —
            instead of failing the batch; refused items' admission deltas
            are rolled back (the write never landed). An optional "fence"
            rejects the WHOLE batch atomically (409 Fenced), exactly like
            the binding subresource."""
            fence = [(str(s), int(t)) for s, t in body.get("fence") or []]
            pairs: list = []
            rollback: dict = {}    # key -> (old, admitted) for refunds
            missing: list = []
            try:
                for d in body["items"]:
                    obj = serde.from_dict(kind, d)
                    try:
                        old = store.get(kind, obj.key)
                    except NotFoundError:
                        missing.append(obj.key)
                        continue
                    obj = admission.admit_update(
                        kind, old, obj, store, user=self._user_name(user))
                    rollback[obj.key] = (old, obj)
                    pairs.append((obj, obj.resource_version or None))
            except AdmissionError as e:
                for old, a in rollback.values():
                    admission.refund_update(kind, old, a, store)
                self._error(422, "Invalid", str(e))
                return
            except (TypeError, ValueError, KeyError) as e:
                for old, a in rollback.values():
                    admission.refund_update(kind, old, a, store)
                self._error(400, "BadRequest", str(e))
                return
            conflicts: list = []
            try:
                stored = store.update_many(
                    kind, pairs, fence=fence or None,
                    conflicts=conflicts, missing=missing) if pairs else []
            except FencedError as e:
                for old, a in rollback.values():
                    admission.refund_update(kind, old, a, store)
                self._error(409, "Fenced", str(e))
                return
            for key in conflicts + missing:
                old, a = rollback.get(key, (None, None))
                if a is not None:   # the admitted write never landed
                    admission.refund_update(kind, old, a, store)
            self._send(200, {"kind": "Status", "status": "Success",
                             "updated": len(stored),
                             "items": [serde.to_dict(s) for s in stored],
                             "conflicts": conflicts, "missing": missing})

        def _serve_PUT(self):
            path, parts, q = self._route()
            # status subresource: PUT /api/v1/podgroups/{ns}/{name}/status
            # {"phase": ..., "members": ..., "scheduled": ...} — status-only
            # write (spec fields untouched), the controller/scheduler verb
            if len(parts) == 6 and parts[2] == PODGROUPS \
                    and parts[5] == "status":
                key = f"{parts[3]}/{parts[4]}"
                user = self._authenticate()
                if not self._authorized(user, "update", PODGROUPS, key):
                    return
                body = self._body()
                try:
                    updated = store.update_pod_group_status(
                        key, phase=body.get("phase"),
                        members=body.get("members"),
                        scheduled=body.get("scheduled"),
                        now=body.get("last_transition_time"))
                except NotFoundError:
                    self._error(404, "NotFound", f"{PODGROUPS}/{key}")
                    return
                except (TypeError, ValueError) as e:
                    self._error(400, "BadRequest", str(e))
                    return
                self._send(200, serde.to_dict(updated))
                return
            if len(parts) == 3 and parts[2] in serde.KIND_TYPES:
                # collection PUT (round 23): {"items": [...]} — the churn
                # plane's batched update, mirroring the round-17
                # collection POST on the mutation side
                kind = parts[2]
                user = self._authenticate()
                if not self._authorized(user, "update", kind):
                    return
                body = self._body()
                if not (isinstance(body, dict) and "items" in body):
                    self._error(400, "BadRequest",
                                "collection PUT takes {\"items\": [...]}")
                    return
                self._update_collection(kind, body, user)
                return
            if len(parts) < 4 or parts[2] not in serde.KIND_TYPES:
                self._error(404, "NotFound", path)
                return
            kind = parts[2]
            user = self._authenticate()
            if not self._authorized(user, "update", kind,
                                    "/".join(parts[3:])):
                return
            old = admitted = None
            try:
                obj = serde.from_dict(kind, self._body())
                # the chain runs on UPDATES too (the reference runs
                # admission on every write verb) — closing the PUT escape
                # hatch around LimitRanger/quota; the old object gives
                # plugins their delta
                old = store.get(kind, obj.key)
                obj = admitted = admission.admit_update(
                    kind, old, obj, store, user=self._user_name(user))
                expect = obj.resource_version or None
                updated = store.update(kind, obj, expect_rv=expect)
            except AdmissionError as e:
                self._error(422, "Invalid", str(e))
                return
            except NotFoundError as e:
                if admitted is not None:   # vanished between admit and write
                    admission.refund_update(kind, old, admitted, store)
                self._error(404, "NotFound", str(e))
                return
            except ConflictError as e:
                # the admitted write never landed: put the delta back
                admission.refund_update(kind, old, admitted, store)
                self._error(409, "Conflict", str(e))
                return
            except (TypeError, ValueError, KeyError) as e:
                self._error(400, "BadRequest", str(e))
                return
            self._send(200, serde.to_dict(updated))

        def _serve_DELETE(self):
            path, parts, q = self._route()
            if len(parts) < 4 or parts[2] not in serde.KIND_TYPES:
                self._error(404, "NotFound", path)
                return
            kind = parts[2]
            key = "/".join(parts[3:])
            user = self._authenticate()
            if not self._authorized(user, "delete", kind, key):
                return
            # deletes run admission too (NodeRestriction: a kubelet may
            # evict only pods bound to its own node)
            try:
                admission.admit_delete(kind, store.get(kind, key), store,
                                       user=self._user_name(user))
            except AdmissionError as e:
                self._error(422, "Invalid", str(e))
                return
            except NotFoundError:
                self._error(404, "NotFound", f"{kind}/{key}")
                return
            from kubernetes_tpu.store.store import NAMESPACES
            if kind == NAMESPACES:
                # namespace finalization (reference: registry/core/namespace
                # storage sets DeletionTimestamp -> phase Terminating; the
                # namespace controller empties it and removes the object)
                def terminate(cur):
                    if cur.phase == "Terminating":
                        return None
                    cur.phase = "Terminating"
                    return cur
                try:
                    gone = store.guaranteed_update(NAMESPACES, key, terminate,
                                                   allow_skip=True)
                except NotFoundError:
                    self._error(404, "NotFound", f"{kind}/{key}")
                    return
                self._send(200, serde.to_dict(gone))
                return
            try:
                gone = store.delete(kind, key)
            except NotFoundError:
                self._error(404, "NotFound", f"{kind}/{key}")
                return
            self._send(200, serde.to_dict(gone))

    return Handler


class APIServer:
    """In-process apiserver: `with APIServer(store) as srv: srv.url`.

    Pass `authenticator` (apiserver.auth.TokenAuthenticator) to require
    bearer tokens, and `authorizer` (RBAC/node/union) to enforce access —
    admission's NodeRestriction then acts on the verified identity."""

    def __init__(self, store: Store, host: str = "127.0.0.1", port: int = 0,
                 admission: AdmissionChain | None = None,
                 authenticator=None, authorizer=None):
        self.store = store
        self.admission = admission or AdmissionChain()
        self._httpd = ThreadingHTTPServer(
            (host, port), make_handler(store, self.admission,
                                       authenticator, authorizer))
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
