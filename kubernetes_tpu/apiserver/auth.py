"""Authentication + authorization for the apiserver.

The reference stacks authenticators (bearer token file among them,
staging/src/k8s.io/apiserver/pkg/authentication/token/tokenfile) in front
of a union of authorizers — RBAC
(plugin/pkg/auth/authorizer/rbac/rbac.go:1) and the node authorizer
(plugin/pkg/auth/authorizer/node/node_authorizer.go:1) being the two that
matter for the control plane. This module provides that floor:

- `TokenAuthenticator`: bearer token -> UserInfo(name, groups); unknown or
  missing tokens are anonymous (None) and the server rejects writes with
  401 when auth is enabled.
- `RBACAuthorizer`: Roles (verb x resource rules, optional resourceNames)
  bound to users/groups; RuleAllows semantics with "*" wildcards
  (rbac.go VisitRulesFor / RuleAllows).
- `NodeAuthorizer`: identities in the `system:nodes` group named
  `system:node:<name>` may read cluster state, write their OWN Node
  object, status-update/delete pods BOUND to them, and create events —
  the graph-based reference collapsed to the ownership rules the
  kubemark-fidelity kubelet exercises.
- `union()`: allow when ANY authorizer allows (the reference's union
  authorizer) — and NodeRestriction then acts on the VERIFIED identity,
  closing the spoofable `X-Remote-User` hole.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

NODES_GROUP = "system:nodes"
NODE_USER_PREFIX = "system:node:"
MASTERS_GROUP = "system:masters"   # cluster-admin bypass, like the reference


@dataclass(frozen=True)
class UserInfo:
    name: str
    groups: tuple[str, ...] = ()


@dataclass(frozen=True)
class Attributes:
    """The authorizer.Attributes subset the REST surface produces."""
    user: UserInfo
    verb: str          # get | list | watch | create | update | delete
    resource: str      # the kind path segment ("pods", "nodes", ...)
    name: str = ""     # object name/key ("" for collection ops)


class TokenAuthenticator:
    """Static token map — the token-file authenticator."""

    def __init__(self, tokens: Optional[dict[str, UserInfo]] = None):
        self.tokens = dict(tokens or {})

    def add(self, token: str, user: UserInfo) -> None:
        self.tokens[token] = user

    def authenticate(self, authorization: Optional[str]) -> Optional[UserInfo]:
        """`Authorization: Bearer <token>` -> UserInfo, else None."""
        if not authorization or not authorization.startswith("Bearer "):
            return None
        return self.tokens.get(authorization[len("Bearer "):])


@dataclass(frozen=True)
class PolicyRule:
    """rbac.PolicyRule subset: verbs x resources (+ optional names)."""
    verbs: tuple[str, ...]
    resources: tuple[str, ...]
    resource_names: tuple[str, ...] = ()

    def allows(self, attrs: Attributes) -> bool:
        # RuleAllows (rbac.go): "*" wildcards, resourceNames narrow to
        # specific objects when present
        if "*" not in self.verbs and attrs.verb not in self.verbs:
            return False
        if "*" not in self.resources and attrs.resource not in self.resources:
            return False
        if self.resource_names:
            return attrs.name in self.resource_names
        return True


@dataclass
class Role:
    """rbac.ClusterRole (cluster-scoped, like everything in this flat
    authorization model). `aggregation_labels` is the ClusterRole
    aggregationRule reduced to match-labels: the clusterrole-aggregation
    controller unions the rules of every role whose `labels` match."""
    name: str
    rules: tuple[PolicyRule, ...] = ()
    labels: dict = field(default_factory=dict)
    aggregation_labels: dict = field(default_factory=dict)
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "Role":
        import copy
        out = copy.copy(self)
        out.labels = dict(self.labels)
        out.aggregation_labels = dict(self.aggregation_labels)
        return out


@dataclass
class RoleBinding:
    """rbac.ClusterRoleBinding: role name + user/group subjects.

    `name` defaults to the role name for the common one-binding-per-role
    case; give EXPLICIT distinct names when storing multiple bindings for
    one role, or their store keys collide (the reference requires
    distinct binding names)."""
    role: str
    name: str = ""
    users: tuple[str, ...] = ()
    groups: tuple[str, ...] = ()
    resource_version: int = 0

    def __post_init__(self):
        if not self.name:
            self.name = self.role

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "RoleBinding":
        import copy
        return copy.copy(self)

    def matches(self, user: UserInfo) -> bool:
        return user.name in self.users or any(
            g in self.groups for g in user.groups)


class RBACAuthorizer:
    """VisitRulesFor over bindings -> roles -> rules (rbac.go:1).

    Static form: pass `roles`/`bindings` literals. Store-backed form: pass
    `store` — every authorize() reads the live clusterroles /
    clusterrolebindings objects, so policy edits through the API take
    effect immediately (the reference's RBAC informers with none of the
    staleness window, affordable at this scale)."""

    def __init__(self, roles: Iterable[Role] = (),
                 bindings: Iterable[RoleBinding] = (), store=None):
        self.roles = {r.name: r for r in roles}
        self.bindings = list(bindings)
        self.store = store

    def _policy(self) -> tuple[dict, list]:
        if self.store is None:
            return self.roles, self.bindings
        from kubernetes_tpu.store.store import CLUSTERROLES, \
            CLUSTERROLEBINDINGS
        roles = {r.name: r for r in self.store.list(CLUSTERROLES)[0]}
        roles.update(self.roles)           # bootstrap literals stay valid
        bindings = self.bindings + self.store.list(CLUSTERROLEBINDINGS)[0]
        return roles, bindings

    def authorize(self, attrs: Attributes) -> bool:
        if MASTERS_GROUP in attrs.user.groups:
            return True
        roles, bindings = self._policy()
        for b in bindings:
            if not b.matches(attrs.user):
                continue
            role = roles.get(b.role)
            if role is None:
                continue
            if any(rule.allows(attrs) for rule in role.rules):
                return True
        return False


# kinds a node identity may NOT read wholesale: the graph-based reference
# authorizer scopes secrets/configmaps/serviceaccounts to the objects
# referenced by pods BOUND to that node (node_authorizer.go:151-186,
# "no relationship found" -> deny); this model keeps no reference graph,
# so the collapse is an outright deny — a compromised kubelet credential
# must not be a read-everything credential for cluster secrets. The
# kubemark-fidelity kubelet reads none of these.
NODE_RESTRICTED_READS = frozenset(
    ("secrets", "configmaps", "serviceaccounts"))


class NodeAuthorizer:
    """node_authorizer.go collapsed to ownership rules: a kubelet identity
    may read cluster state (its informers) EXCEPT secret-bearing kinds,
    write only its own Node, touch only pods bound to it, and post
    events."""

    def authorize(self, attrs: Attributes) -> bool:
        u = attrs.user
        if NODES_GROUP not in u.groups or \
                not u.name.startswith(NODE_USER_PREFIX):
            return False
        node_name = u.name[len(NODE_USER_PREFIX):]
        if attrs.resource in NODE_RESTRICTED_READS:
            return False
        if attrs.verb in ("get", "list", "watch"):
            return True
        if attrs.resource == "nodes":
            # create-on-register + self-updates only
            return attrs.name in ("", node_name) and \
                attrs.verb in ("create", "update")
        if attrs.resource == "leases":
            # node heartbeat lease, named after the node
            return attrs.name in ("", node_name)
        if attrs.resource == "events":
            return attrs.verb == "create"
        if attrs.resource == "pods":
            # status updates and eviction of pods on this node; WHICH pods
            # is enforced by NodeRestriction admission against the object.
            # No "create": binding subresources are the scheduler's verb,
            # and the kubemark kubelet runs no mirror pods.
            return attrs.verb in ("update", "delete")
        return False


class UnionAuthorizer:
    def __init__(self, *authorizers):
        self.authorizers = [a for a in authorizers if a is not None]

    def authorize(self, attrs: Attributes) -> bool:
        return any(a.authorize(attrs) for a in self.authorizers)


def union(*authorizers) -> UnionAuthorizer:
    return UnionAuthorizer(*authorizers)


# the control-plane roles a bootstrapped cluster grants
# (bootstrappolicy analog): scheduler and controller-manager identities
def default_roles() -> tuple[list[Role], list[RoleBinding]]:
    roles = [
        Role("system:kube-scheduler", rules=(
            PolicyRule(verbs=("get", "list", "watch"), resources=("*",)),
            PolicyRule(verbs=("create", "update", "delete"),
                       resources=("pods", "events", "leases")),
        )),
        Role("system:kube-controller-manager", rules=(
            PolicyRule(verbs=("*",), resources=("*",)),
        )),
        Role("system:public-info-viewer", rules=(
            PolicyRule(verbs=("get", "list", "watch"), resources=("*",)),
        )),
    ]
    bindings = [
        RoleBinding("system:kube-scheduler",
                    users=("system:kube-scheduler",)),
        RoleBinding("system:kube-controller-manager",
                    users=("system:kube-controller-manager",)),
    ]
    return roles, bindings
