"""Admission chain — plugin/pkg/admission analog.

Mutating/validating plugins run on every apiserver write before the store
commit (the reference chains 20+ plugins in the generic apiserver's
handler stack). Implemented plugins:

- PriorityAdmission (plugin/pkg/admission/priority): resolves
  pod.priority_class_name to the PriorityClass value (or the cluster's
  global default when unset), writing pod.priority — the field preemption
  orders by. Unknown class names are rejected.
- TaintNodesByCondition-style defaulting is NOT admission here (the
  node-lifecycle controller owns taints).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from kubernetes_tpu.store.store import Store, PODS, PRIORITYCLASSES


class AdmissionError(Exception):
    """Write rejected (HTTP 422 at the REST boundary)."""


class PriorityAdmission:
    """plugin/pkg/admission/priority: Admit on pod create."""

    kind = PODS

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        classes, _rv = store.list(PRIORITYCLASSES)
        if obj.priority_class_name:
            for pc in classes:
                if pc.name == obj.priority_class_name:
                    obj.priority = pc.value
                    return obj
            raise AdmissionError(
                f"no PriorityClass with name {obj.priority_class_name} was found")
        for pc in classes:
            if pc.global_default:
                obj.priority = pc.value
                obj.priority_class_name = pc.name
                return obj
        return obj   # resolved priority 0 (the reference's default)


class DefaultTolerationSeconds:
    """plugin/pkg/admission/defaulttolerationseconds: pods that don't pin
    their own not-ready/unreachable NoExecute tolerations get the cluster
    defaults (300s), bounding how long they linger on a failed node before
    the taint manager evicts them."""

    DEFAULT_SECONDS = 300.0

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.api.types import Toleration, TOLERATION_OP_EXISTS
        from kubernetes_tpu.controllers.nodelifecycle import (
            TAINT_NOT_READY, TAINT_UNREACHABLE)
        have = {t.key for t in obj.tolerations
                if t.effect in ("", "NoExecute")}
        extra = []
        for key in (TAINT_NOT_READY, TAINT_UNREACHABLE):
            if key not in have:
                extra.append(Toleration(
                    key=key, op=TOLERATION_OP_EXISTS, effect="NoExecute",
                    toleration_seconds=self.DEFAULT_SECONDS))
        if extra:
            obj.tolerations = obj.tolerations + tuple(extra)
        return obj


class LimitRanger:
    """plugin/pkg/admission/limitranger (defaulting half): containers with
    no cpu/memory request get the configured defaults, so every pod the
    scheduler sees has concrete resource demands."""

    def __init__(self, default_cpu: int = 100, default_mem: int = 200 * 1024 ** 2):
        self.default_cpu = default_cpu
        self.default_mem = default_mem

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.api.types import Container
        changed = False
        out = []
        for c in obj.containers:
            req = dict(c.requests)
            if "cpu" not in req or "memory" not in req:
                req.setdefault("cpu", self.default_cpu)
                req.setdefault("memory", self.default_mem)
                c = Container(name=c.name, image=c.image,
                              requests=tuple(sorted(req.items())),
                              limits=c.limits, ports=c.ports)
                changed = True
            out.append(c)
        if changed:
            obj.containers = tuple(out)
        return obj


class ResourceQuotaAdmission:
    """plugin/pkg/admission/resourcequota: reject pod creation that would
    push any namespace quota past its hard caps, COMMITTING the new usage
    synchronously via CAS on admit (the reference's checkQuotas CASes quota
    status through the evaluator before the pod write lands,
    plugin/pkg/admission/resourcequota/controller.go). A rapid burst of
    creates therefore cannot overshoot: each admit observes the previous
    admit's committed usage. The controller reconciles drift (pod deletes,
    terminal phases) from live state afterwards."""

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.store.store import RESOURCEQUOTAS, NotFoundError
        from kubernetes_tpu.controllers.resourcequota import pod_usage
        quotas, _rv = store.list(RESOURCEQUOTAS)
        matching = [q for q in quotas
                    if q.namespace == obj.namespace and q.hard]
        if not matching:
            return obj
        usage = pod_usage(obj)

        def charge(cur):
            over = [
                f"{name}: used {cur.used.get(name, 0)} + requested "
                f"{usage.get(name, 0)} > hard {cap}"
                for name, cap in cur.hard.items()
                if cur.used.get(name, 0) + usage.get(name, 0) > cap]
            if over:
                raise AdmissionError(
                    f"exceeded quota {cur.key}: " + "; ".join(over))
            used = dict(cur.used)
            for name in cur.hard:
                if usage.get(name):
                    used[name] = used.get(name, 0) + usage[name]
            cur.used = used
            return cur

        def refund(cur):
            used = dict(cur.used)
            for name in cur.hard:
                if usage.get(name):
                    used[name] = max(0, used.get(name, 0) - usage[name])
            cur.used = used
            return cur

        charged: list[str] = []
        try:
            for q in matching:
                store.guaranteed_update(RESOURCEQUOTAS, q.key, charge)
                charged.append(q.key)
        except AdmissionError:
            # a later quota rejected after earlier ones were charged: put
            # the earlier charges back before surfacing the rejection
            self._refund_keys(store, charged, usage)
            raise
        return obj

    def _refund_keys(self, store: Store, keys, usage) -> None:
        from kubernetes_tpu.store.store import RESOURCEQUOTAS, NotFoundError

        def refund(cur):
            used = dict(cur.used)
            for name in cur.hard:
                if usage.get(name):
                    used[name] = max(0, used.get(name, 0) - usage[name])
            cur.used = used
            return cur

        for key in keys:
            try:
                store.guaranteed_update(RESOURCEQUOTAS, key, refund)
            except NotFoundError:
                pass

    def refund(self, kind: str, obj: Any, store: Store) -> None:
        """Undo admit()'s usage commit when the admitted write itself fails
        (AlreadyExists/Conflict): without this, every failed create leaks a
        permanent charge against the namespace quotas."""
        if kind != PODS:
            return
        from kubernetes_tpu.store.store import RESOURCEQUOTAS
        from kubernetes_tpu.controllers.resourcequota import pod_usage
        quotas, _rv = store.list(RESOURCEQUOTAS)
        keys = [q.key for q in quotas
                if q.namespace == obj.namespace and q.hard]
        if keys:
            self._refund_keys(store, keys, pod_usage(obj))


class AdmissionChain:
    def __init__(self, plugins: Optional[list] = None):
        # ResourceQuotaAdmission runs LAST: its admit commits quota usage,
        # and only a failure of the store write itself (handled by the
        # caller via refund()) — not a later plugin's rejection — may
        # follow a successful charge
        self.plugins = plugins if plugins is not None else [
            PriorityAdmission(), DefaultTolerationSeconds(), LimitRanger(),
            ResourceQuotaAdmission()]

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        for p in self.plugins:
            obj = p.admit(kind, obj, store)
        return obj

    def refund(self, kind: str, obj: Any, store: Store) -> None:
        """Roll back side-effecting admissions (quota usage commits) after
        the admitted write failed to land (AlreadyExists/Conflict). Callers
        that admit-then-create MUST call this on create failure."""
        for p in self.plugins:
            r = getattr(p, "refund", None)
            if r is not None:
                r(kind, obj, store)
