"""Admission chain — plugin/pkg/admission analog.

Mutating/validating plugins run on every apiserver write before the store
commit (the reference chains 20+ plugins in the generic apiserver's
handler stack). Implemented plugins:

- PriorityAdmission (plugin/pkg/admission/priority): resolves
  pod.priority_class_name to the PriorityClass value (or the cluster's
  global default when unset), writing pod.priority — the field preemption
  orders by. Unknown class names are rejected.
- TaintNodesByCondition-style defaulting is NOT admission here (the
  node-lifecycle controller owns taints).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from kubernetes_tpu.store.store import Store, PODS, PRIORITYCLASSES


class AdmissionError(Exception):
    """Write rejected (HTTP 422 at the REST boundary)."""


class PriorityAdmission:
    """plugin/pkg/admission/priority: Admit on pod create."""

    kind = PODS

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        classes, _rv = store.list(PRIORITYCLASSES)
        if obj.priority_class_name:
            for pc in classes:
                if pc.name == obj.priority_class_name:
                    obj.priority = pc.value
                    return obj
            raise AdmissionError(
                f"no PriorityClass with name {obj.priority_class_name} was found")
        for pc in classes:
            if pc.global_default:
                obj.priority = pc.value
                obj.priority_class_name = pc.name
                return obj
        return obj   # resolved priority 0 (the reference's default)


class AdmissionChain:
    def __init__(self, plugins: Optional[list] = None):
        self.plugins = plugins if plugins is not None else [PriorityAdmission()]

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        for p in self.plugins:
            obj = p.admit(kind, obj, store)
        return obj
