"""Admission chain — plugin/pkg/admission analog.

Mutating/validating plugins run on every apiserver write before the store
commit (the reference chains 20+ plugins in the generic apiserver's
handler stack). Implemented plugins:

- PriorityAdmission (plugin/pkg/admission/priority): resolves
  pod.priority_class_name to the PriorityClass value (or the cluster's
  global default when unset), writing pod.priority — the field preemption
  orders by. Unknown class names are rejected.
- TaintNodesByCondition-style defaulting is NOT admission here (the
  node-lifecycle controller owns taints).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from kubernetes_tpu.store.store import Store, PODS, PRIORITYCLASSES


class AdmissionError(Exception):
    """Write rejected (HTTP 422 at the REST boundary)."""


class PriorityAdmission:
    """plugin/pkg/admission/priority: Admit on pod create."""

    kind = PODS

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        classes, _rv = store.list(PRIORITYCLASSES)
        if obj.priority_class_name:
            for pc in classes:
                if pc.name == obj.priority_class_name:
                    obj.priority = pc.value
                    return obj
            raise AdmissionError(
                f"no PriorityClass with name {obj.priority_class_name} was found")
        for pc in classes:
            if pc.global_default:
                obj.priority = pc.value
                obj.priority_class_name = pc.name
                return obj
        return obj   # resolved priority 0 (the reference's default)


class DefaultTolerationSeconds:
    """plugin/pkg/admission/defaulttolerationseconds: pods that don't pin
    their own not-ready/unreachable NoExecute tolerations get the cluster
    defaults (300s), bounding how long they linger on a failed node before
    the taint manager evicts them."""

    DEFAULT_SECONDS = 300.0

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.api.types import Toleration, TOLERATION_OP_EXISTS
        from kubernetes_tpu.controllers.nodelifecycle import (
            TAINT_NOT_READY, TAINT_UNREACHABLE)
        have = {t.key for t in obj.tolerations
                if t.effect in ("", "NoExecute")}
        extra = []
        for key in (TAINT_NOT_READY, TAINT_UNREACHABLE):
            if key not in have:
                extra.append(Toleration(
                    key=key, op=TOLERATION_OP_EXISTS, effect="NoExecute",
                    toleration_seconds=self.DEFAULT_SECONDS))
        if extra:
            obj.tolerations = obj.tolerations + tuple(extra)
        return obj


class LimitRanger:
    """plugin/pkg/admission/limitranger (defaulting half): containers with
    no cpu/memory request get the configured defaults, so every pod the
    scheduler sees has concrete resource demands."""

    def __init__(self, default_cpu: int = 100, default_mem: int = 200 * 1024 ** 2):
        self.default_cpu = default_cpu
        self.default_mem = default_mem

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.api.types import Container
        changed = False
        out = []
        for c in obj.containers:
            req = dict(c.requests)
            if "cpu" not in req or "memory" not in req:
                req.setdefault("cpu", self.default_cpu)
                req.setdefault("memory", self.default_mem)
                c = Container(name=c.name, image=c.image,
                              requests=tuple(sorted(req.items())),
                              limits=c.limits, ports=c.ports)
                changed = True
            out.append(c)
        if changed:
            obj.containers = tuple(out)
        return obj

    def admit_update(self, kind: str, old: Any, new: Any, store: Store) -> Any:
        # the reference LimitRanger runs on updates too: a PUT must not
        # strip the defaults a create received
        return self.admit(kind, new, store)


def _subtract_usage(cur, amounts: dict) -> Any:
    """Clamp-at-zero usage decrement over a quota's hard-capped resources —
    the single mutate every quota refund path shares."""
    used = dict(cur.used)
    for name in cur.hard:
        if amounts.get(name):
            used[name] = max(0, used.get(name, 0) - amounts[name])
    cur.used = used
    return cur


class ResourceQuotaAdmission:
    """plugin/pkg/admission/resourcequota: reject pod creation that would
    push any namespace quota past its hard caps, COMMITTING the new usage
    synchronously via CAS on admit (the reference's checkQuotas CASes quota
    status through the evaluator before the pod write lands,
    plugin/pkg/admission/resourcequota/controller.go). A rapid burst of
    creates therefore cannot overshoot: each admit observes the previous
    admit's committed usage. The controller reconciles drift (pod deletes,
    terminal phases) from live state afterwards."""

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.store.store import RESOURCEQUOTAS, NotFoundError
        from kubernetes_tpu.controllers.resourcequota import pod_usage
        quotas, _rv = store.list(RESOURCEQUOTAS)
        matching = [q for q in quotas
                    if q.namespace == obj.namespace and q.hard]
        if not matching:
            return obj
        usage = pod_usage(obj)

        def charge(cur):
            over = [
                f"{name}: used {cur.used.get(name, 0)} + requested "
                f"{usage.get(name, 0)} > hard {cap}"
                for name, cap in cur.hard.items()
                if cur.used.get(name, 0) + usage.get(name, 0) > cap]
            if over:
                raise AdmissionError(
                    f"exceeded quota {cur.key}: " + "; ".join(over))
            used = dict(cur.used)
            for name in cur.hard:
                if usage.get(name):
                    used[name] = used.get(name, 0) + usage[name]
            cur.used = used
            return cur

        charged: list[str] = []
        try:
            for q in matching:
                store.guaranteed_update(RESOURCEQUOTAS, q.key, charge)
                charged.append(q.key)
        except AdmissionError:
            # a later quota rejected after earlier ones were charged: put
            # the earlier charges back before surfacing the rejection
            self._refund_keys(store, charged, usage)
            raise
        return obj

    def _refund_keys(self, store: Store, keys, usage) -> None:
        from kubernetes_tpu.store.store import RESOURCEQUOTAS, NotFoundError
        for key in keys:
            try:
                store.guaranteed_update(
                    RESOURCEQUOTAS, key,
                    lambda cur: _subtract_usage(cur, usage))
            except NotFoundError:
                pass

    def refund(self, kind: str, obj: Any, store: Store) -> None:
        """Undo admit()'s usage commit when the admitted write itself fails
        (AlreadyExists/Conflict): without this, every failed create leaks a
        permanent charge against the namespace quotas."""
        if kind != PODS:
            return
        from kubernetes_tpu.store.store import RESOURCEQUOTAS
        from kubernetes_tpu.controllers.resourcequota import pod_usage
        quotas, _rv = store.list(RESOURCEQUOTAS)
        keys = [q.key for q in quotas
                if q.namespace == obj.namespace and q.hard]
        if keys:
            self._refund_keys(store, keys, pod_usage(obj))

    def admit_update(self, kind: str, old: Any, new: Any, store: Store) -> Any:
        """The classic escape hatch this closes: create a conforming pod,
        PUT it oversized. Charges/refunds the usage DELTA via the same CAS
        (negative deltas replenish immediately; the controller reconciles
        any drift)."""
        if kind != PODS:
            return new
        from kubernetes_tpu.store.store import RESOURCEQUOTAS, NotFoundError
        from kubernetes_tpu.controllers.resourcequota import pod_usage
        quotas, _rv = store.list(RESOURCEQUOTAS)
        matching = [q for q in quotas
                    if q.namespace == new.namespace and q.hard]
        if not matching:
            return new
        old_u, new_u = pod_usage(old), pod_usage(new)
        delta = {k: new_u.get(k, 0) - old_u.get(k, 0)
                 for k in set(old_u) | set(new_u)}
        if not any(delta.values()):
            return new

        def apply(cur):
            # only GROWING resources are checked (the reference rejects only
            # usage increases past hard): an already-over-cap namespace —
            # e.g. after an admin lowered the cap — must not block shrinking
            # or unrelated updates
            over = [
                f"{name}: used {cur.used.get(name, 0)} + delta "
                f"{delta.get(name, 0)} > hard {cap}"
                for name, cap in cur.hard.items()
                if delta.get(name, 0) > 0
                and cur.used.get(name, 0) + delta.get(name, 0) > cap]
            if over:
                raise AdmissionError(
                    f"exceeded quota {cur.key}: " + "; ".join(over))
            used = dict(cur.used)
            for name in cur.hard:
                if delta.get(name):
                    used[name] = max(0, used.get(name, 0) + delta[name])
            cur.used = used
            return cur

        charged: list[str] = []
        try:
            for q in matching:
                store.guaranteed_update(RESOURCEQUOTAS, q.key, apply)
                charged.append(q.key)
        except AdmissionError:
            for key in charged:
                try:
                    store.guaranteed_update(
                        RESOURCEQUOTAS, key,
                        lambda cur: _subtract_usage(cur, delta))
                except NotFoundError:
                    pass
            raise
        return new

    def refund_update(self, kind: str, old: Any, new: Any,
                      store: Store) -> None:
        """Inverse of admit_update's delta charge, for a PUT that failed to
        land (Conflict/NotFound)."""
        if kind != PODS:
            return
        from kubernetes_tpu.store.store import RESOURCEQUOTAS, NotFoundError
        from kubernetes_tpu.controllers.resourcequota import pod_usage
        old_u, new_u = pod_usage(old), pod_usage(new)
        delta = {k: new_u.get(k, 0) - old_u.get(k, 0)
                 for k in set(old_u) | set(new_u)}
        if not any(delta.values()):
            return
        quotas, _rv = store.list(RESOURCEQUOTAS)
        for q in quotas:
            if q.namespace != new.namespace or not q.hard:
                continue
            try:
                store.guaranteed_update(
                    RESOURCEQUOTAS, q.key,
                    lambda cur: _subtract_usage(cur, delta))
            except NotFoundError:
                pass


class NodeRestriction:
    """plugin/pkg/admission/noderestriction/admission.go:46: a kubelet
    identity (`system:node:<name>`) may only update ITS OWN Node object and
    pods bound to its node. Identity arrives as the REST layer's
    `X-Remote-User` header (the reference's header authn front end); writes
    with no user (in-process controllers, admins) are unrestricted."""

    PREFIX = "system:node:"

    def _node_of(self, user: Optional[str]) -> Optional[str]:
        if user and user.startswith(self.PREFIX):
            return user[len(self.PREFIX):]
        return None

    def admit(self, kind: str, obj: Any, store: Store,
              user: Optional[str] = None) -> Any:
        from kubernetes_tpu.store.store import NODES
        node = self._node_of(user)
        if node is None:
            return obj
        if kind == NODES and obj.name != node:
            raise AdmissionError(
                f"node {node!r} is not allowed to modify node {obj.name!r}")
        if kind == PODS and getattr(obj, "node_name", "") not in ("", node):
            raise AdmissionError(
                f"node {node!r} is not allowed to modify pods bound to "
                f"node {obj.node_name!r}")
        return obj

    def admit_update(self, kind: str, old: Any, new: Any, store: Store,
                     user: Optional[str] = None) -> Any:
        node = self._node_of(user)
        if node is not None and kind == PODS \
                and getattr(old, "node_name", "") not in ("", node):
            # the OLD binding counts too: a kubelet may not unbind/steal a
            # pod bound to another node by rewriting node_name in the body
            raise AdmissionError(
                f"node {node!r} is not allowed to modify pods bound to "
                f"node {old.node_name!r}")
        return self.admit(kind, new, store, user=user)

    def admit_binding(self, pod: Any, node_name: str, store: Store,
                      user: Optional[str] = None) -> None:
        # binding is the scheduler's verb: a node identity may not bind
        # (or steal) pods at all (admission.go:46 posture; kubelets report
        # status, they do not place workloads)
        node = self._node_of(user)
        if node is not None:
            raise AdmissionError(
                f"node {node!r} is not allowed to create pod bindings")

    def admit_delete(self, kind: str, obj: Any, store: Store,
                     user: Optional[str] = None) -> None:
        from kubernetes_tpu.store.store import NODES
        node = self._node_of(user)
        if node is None:
            return
        if kind == PODS and getattr(obj, "node_name", "") != node:
            # ONLY pods bound to this node — an unbound pod is the
            # scheduler's, not any kubelet's, so a stolen node credential
            # can't drain the pending queue
            raise AdmissionError(
                f"node {node!r} is not allowed to delete pods bound to "
                f"node {obj.node_name or '<none>'!r}")
        if kind == NODES and obj.name != node:
            raise AdmissionError(
                f"node {node!r} is not allowed to delete node {obj.name!r}")


class PodTolerationRestriction:
    """plugin/pkg/admission/podtolerationrestriction: merge the namespace's
    default tolerations into the pod and reject tolerations outside the
    namespace whitelist (both from namespace annotations, as JSON lists of
    {key, operator, value, effect})."""

    DEFAULT_KEY = "scheduler.alpha.kubernetes.io/defaultTolerations"
    WHITELIST_KEY = "scheduler.alpha.kubernetes.io/tolerationsWhitelist"

    @staticmethod
    def _parse(raw: str):
        import json as _json
        from kubernetes_tpu.api.types import Toleration
        out = []
        for d in _json.loads(raw):
            out.append(Toleration(
                key=d.get("key", ""), op=d.get("operator", "Equal"),
                value=d.get("value", ""), effect=d.get("effect", "")))
        return out

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.store.store import NAMESPACES, NotFoundError
        try:
            ns = store.get(NAMESPACES, obj.namespace)
        except NotFoundError:
            return obj
        if self.DEFAULT_KEY in ns.annotations:
            defaults = self._parse(ns.annotations[self.DEFAULT_KEY])
            have = set(obj.tolerations)
            extra = tuple(t for t in defaults if t not in have)
            if extra:
                obj.tolerations = obj.tolerations + extra
        if self.WHITELIST_KEY in ns.annotations:
            allowed = set(self._parse(ns.annotations[self.WHITELIST_KEY]))
            bad = [t for t in obj.tolerations if t not in allowed]
            if bad:
                raise AdmissionError(
                    f"pod tolerations (possibly merged) conflict with "
                    f"namespace whitelist of {obj.namespace}")
        return obj

    def admit_update(self, kind: str, old: Any, new: Any, store: Store) -> Any:
        # the reference registers for Create AND Update — a PUT must not
        # smuggle in tolerations the namespace forbids. The cluster NoExecute
        # defaults (DefaultTolerationSeconds) were added on create and sit in
        # `new` already; whitelist them implicitly by judging only the diff
        # against old's accepted set when a whitelist exists.
        if kind != PODS:
            return new
        from kubernetes_tpu.store.store import NAMESPACES, NotFoundError
        try:
            ns = store.get(NAMESPACES, new.namespace)
        except NotFoundError:
            return new
        if self.WHITELIST_KEY in ns.annotations:
            allowed = set(self._parse(ns.annotations[self.WHITELIST_KEY]))
            allowed |= set(old.tolerations)
            bad = [t for t in new.tolerations if t not in allowed]
            if bad:
                raise AdmissionError(
                    f"pod tolerations conflict with namespace whitelist "
                    f"of {new.namespace}")
        return new


class AntiAffinityAdmission:
    """plugin/pkg/admission/antiaffinity (LimitPodHardAntiAffinityTopology):
    required pod anti-affinity with a topology key other than the hostname
    label is rejected — cluster-wide anti-affinity is an abuse vector."""

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        if kind != PODS:
            return obj
        from kubernetes_tpu.api.types import LABEL_HOSTNAME
        a = getattr(obj, "affinity", None)
        paa = a.pod_anti_affinity if a is not None else None
        for term in (paa.required if paa else ()):
            if term.topology_key != LABEL_HOSTNAME:
                raise AdmissionError(
                    "affinity.podAntiAffinity.requiredDuringScheduling... "
                    f"topologyKey {term.topology_key!r} is not allowed "
                    f"(only {LABEL_HOSTNAME})")
        return obj

    def admit_update(self, kind: str, old: Any, new: Any, store: Store) -> Any:
        return self.admit(kind, new, store)


class ServiceAccountAdmission:
    """plugin/pkg/admission/serviceaccount/admission.go: default every pod
    to the namespace's 'default' ServiceAccount and reject pods naming an
    account that doesn't exist (the reference also mounts token volumes —
    no volume dataplane exists in this model, so the identity half is the
    faithful subset)."""

    def admit(self, kind: str, obj: Any, store: Store,
              user: Optional[str] = None) -> Any:
        from kubernetes_tpu.store.store import SERVICEACCOUNTS, NotFoundError
        if kind != PODS:
            return obj
        if not obj.service_account_name:
            obj.service_account_name = "default"
        try:
            store.get(SERVICEACCOUNTS,
                      f"{obj.namespace}/{obj.service_account_name}")
        except NotFoundError:
            # the reference retries for a short window to ride out the SA
            # controller's default creation; our controller creates
            # 'default' on namespace sight, so only a truly missing named
            # account rejects (and a missing 'default' in a namespace the
            # controller never saw admits — matching the reference's
            # bootstrapping tolerance for the default account)
            if obj.service_account_name != "default":
                raise AdmissionError(
                    f"service account {obj.namespace}/"
                    f"{obj.service_account_name} does not exist")
        return obj

    def admit_update(self, kind: str, old: Any, new: Any, store: Store,
                     user: Optional[str] = None) -> Any:
        # a PUT must not smuggle in a nonexistent account (the chain runs
        # admission on every write verb)
        return self.admit(kind, new, store, user=user)


class EventRateLimit:
    """plugin/pkg/admission/eventratelimit: a token bucket over event
    creates (server scope) so an event storm cannot swamp the store."""

    def __init__(self, qps: float = 50.0, burst: int = 100, clock=None):
        import threading
        import time as _time
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = None
        self._now = clock or _time.monotonic
        # the chain runs inside ThreadingHTTPServer request threads; the
        # read-modify-write of the bucket must not race
        self._lock = threading.Lock()

    def admit(self, kind: str, obj: Any, store: Store) -> Any:
        from kubernetes_tpu.store.store import EVENTS
        if kind != EVENTS:
            return obj
        with self._lock:
            now = self._now()
            if self._last is not None:
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens < 1.0:
                raise AdmissionError(
                    "event rate limited (server bucket empty)")
            self._tokens -= 1.0
        return obj


class AdmissionChain:
    def __init__(self, plugins: Optional[list] = None):
        # ResourceQuotaAdmission runs LAST: its admit commits quota usage,
        # and only a failure of the store write itself (handled by the
        # caller via refund()) — not a later plugin's rejection — may
        # follow a successful charge
        # PodTolerationRestriction precedes DefaultTolerationSeconds (as in
        # the reference's recommended order) so namespace whitelists judge
        # the POD'S tolerations, not the cluster-injected NoExecute defaults
        self.plugins = plugins if plugins is not None else [
            NodeRestriction(), PriorityAdmission(),
            ServiceAccountAdmission(), PodTolerationRestriction(),
            AntiAffinityAdmission(), EventRateLimit(),
            DefaultTolerationSeconds(), LimitRanger(),
            ResourceQuotaAdmission()]

    def admit(self, kind: str, obj: Any, store: Store,
              user: Optional[str] = None) -> Any:
        for p in self.plugins:
            if user is not None and isinstance(p, NodeRestriction):
                obj = p.admit(kind, obj, store, user=user)
            else:
                obj = p.admit(kind, obj, store)
        return obj

    def register_webhooks(self, webhook_admission) -> None:
        """Insert a WebhookAdmission BEFORE ResourceQuotaAdmission: quota
        must stay last (its admit commits usage, and only a store-write
        failure — refunded by the caller — may follow a successful
        charge; a webhook denial after the charge would leak it). The
        reference's recommended order also runs the admission webhooks
        before ResourceQuota."""
        for i, p in enumerate(self.plugins):
            if isinstance(p, ResourceQuotaAdmission):
                self.plugins.insert(i, webhook_admission)
                return
        self.plugins.append(webhook_admission)

    def admit_binding(self, pod: Any, node_name: str, store: Store,
                      user: Optional[str] = None) -> None:
        """Admission for the pods/binding subresource (the scheduler's
        write verb, factory.go:710): plugins exposing admit_binding judge
        (current pod, target node, identity) — NodeRestriction uses it to
        keep node identities from binding/stealing pods."""
        for p in self.plugins:
            ab = getattr(p, "admit_binding", None)
            if ab is not None:
                ab(pod, node_name, store, user=user)

    def admit_delete(self, kind: str, obj: Any, store: Store,
                     user: Optional[str] = None) -> None:
        """Admission for deletes: plugins exposing admit_delete judge the
        object about to go away (NodeRestriction: a kubelet may evict only
        pods bound to its own node, delete only its own Node)."""
        for p in self.plugins:
            ad = getattr(p, "admit_delete", None)
            if ad is not None:
                ad(kind, obj, store, user=user)

    def admit_update(self, kind: str, old: Any, new: Any, store: Store,
                     user: Optional[str] = None) -> Any:
        """The UPDATE half of the chain (the reference runs admission on
        every write verb): plugins exposing admit_update participate; pure
        create-defaulting plugins are skipped."""
        for p in self.plugins:
            au = getattr(p, "admit_update", None)
            if au is None:
                continue
            if isinstance(p, NodeRestriction):
                new = au(kind, old, new, store, user=user)
            else:
                new = au(kind, old, new, store)
        return new

    def refund(self, kind: str, obj: Any, store: Store) -> None:
        """Roll back side-effecting admissions (quota usage commits) after
        the admitted write failed to land (AlreadyExists/Conflict). Callers
        that admit-then-create MUST call this on create failure."""
        for p in self.plugins:
            r = getattr(p, "refund", None)
            if r is not None:
                r(kind, obj, store)

    def refund_update(self, kind: str, old: Any, new: Any,
                      store: Store) -> None:
        """Roll back admit_update side effects (quota delta charges) after
        the admitted PUT failed to land (Conflict/NotFound)."""
        for p in self.plugins:
            r = getattr(p, "refund_update", None)
            if r is not None:
                r(kind, old, new, store)
