"""Webhook admission — the dynamic admission extension point.

Mirror of the reference's mutating/validating admission webhooks
(staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/{mutating,
validating}): registrations name a kind set + operations, the chain calls
each matching webhook with an AdmissionReview-shaped payload, mutating
webhooks return a patched object, validating webhooks allow/deny, and an
unreachable webhook follows its failurePolicy (Ignore = admit anyway,
Fail = reject the write). Transport is the extender pattern
(core/extender.py): an in-process callable or a real HTTP JSON endpoint.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from kubernetes_tpu.api import serde
from kubernetes_tpu.apiserver.admission import AdmissionError

FAIL = "Fail"          # failurePolicy values (webhook types.go)
IGNORE = "Ignore"


@dataclass
class WebhookConfig:
    """One registration (Mutating/ValidatingWebhookConfiguration entry)."""
    name: str
    kinds: tuple[str, ...] = ("*",)         # store kinds ("pods", ...)
    operations: tuple[str, ...] = ("CREATE", "UPDATE")
    failure_policy: str = FAIL
    url: str = ""                            # HTTP endpoint; or...
    endpoint: Optional[Callable[[dict], dict]] = None   # in-process callable
    timeout: float = 10.0

    def matches(self, kind: str, operation: str) -> bool:
        return (("*" in self.kinds or kind in self.kinds)
                and operation in self.operations)


class Webhook:
    def __init__(self, config: WebhookConfig, mutating: bool):
        self.config = config
        self.mutating = mutating

    def _call(self, payload: dict) -> dict:
        if self.config.endpoint is not None:
            return self.config.endpoint(payload)
        req = urllib.request.Request(
            self.config.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req,
                                    timeout=self.config.timeout) as resp:
            return json.loads(resp.read() or b"{}")

    def review(self, kind: str, operation: str, obj: Any,
               old: Any = None) -> Any:
        """One AdmissionReview round trip. Returns the (possibly patched)
        object; raises AdmissionError on deny or on transport failure with
        failurePolicy Fail."""
        payload = {
            "kind": kind,
            "operation": operation,
            "object": serde.to_dict(obj),
            "oldObject": serde.to_dict(old) if old is not None else None,
        }
        try:
            resp = self._call(payload)
        except (urllib.error.URLError, OSError, ValueError) as e:
            if self.config.failure_policy == IGNORE:
                return obj   # unreachable + Ignore: admit unchanged
            raise AdmissionError(
                f"webhook {self.config.name!r} failed: {e}")
        if not resp.get("allowed", False):
            raise AdmissionError(
                f"admission webhook {self.config.name!r} denied the "
                f"request: {resp.get('message', '')}")
        if self.mutating and resp.get("patchedObject") is not None:
            patched = serde.from_dict(kind, resp["patchedObject"])
            # a patch may not move or re-version the object: identity
            # metadata is re-pinned from the pre-patch object (the
            # reference rejects webhook mutations of immutable metadata;
            # a zeroed resource_version would silently disable the PUT's
            # optimistic-concurrency check)
            for attr in ("name", "namespace", "uid", "resource_version"):
                if hasattr(patched, attr) and hasattr(obj, attr):
                    setattr(patched, attr, getattr(obj, attr))
            return patched
        return obj


@dataclass
class WebhookAdmission:
    """The chain plugin hosting every registered webhook: mutating first
    (their patches feed the next), then validating against the final
    object — the reference's two-phase order."""
    mutating: list[Webhook] = field(default_factory=list)
    validating: list[Webhook] = field(default_factory=list)

    def register_mutating(self, config: WebhookConfig) -> None:
        self.mutating.append(Webhook(config, mutating=True))

    def register_validating(self, config: WebhookConfig) -> None:
        self.validating.append(Webhook(config, mutating=False))

    def _run(self, kind: str, operation: str, obj: Any,
             old: Any = None) -> Any:
        for w in self.mutating:
            if w.config.matches(kind, operation):
                obj = w.review(kind, operation, obj, old)
        for w in self.validating:
            if w.config.matches(kind, operation):
                w.review(kind, operation, obj, old)
        return obj

    # -- AdmissionChain plugin surface --------------------------------------
    def admit(self, kind: str, obj: Any, store,
              user: Optional[str] = None) -> Any:
        return self._run(kind, "CREATE", obj)

    def admit_update(self, kind: str, old: Any, new: Any, store,
                     user: Optional[str] = None) -> Any:
        return self._run(kind, "UPDATE", new, old)

    def admit_delete(self, kind: str, obj: Any, store,
                     user: Optional[str] = None) -> None:
        # DELETE reviews are validating-only (nothing to patch: the object
        # is going away); a mutating registration matching DELETE is
        # treated as validating, like the reference's DELETE reviews
        for w in self.mutating + self.validating:
            if w.config.matches(kind, "DELETE"):
                w.review(kind, "DELETE", obj)
