"""kube-proxy analog — a per-node virtual service dataplane.

The reference's iptables proxier (pkg/proxy/iptables/proxier.go) runs on
every node, watches Services + Endpoints, and on each sync REBUILDS the
kernel rule set: one service chain per service, one endpoint chain per
backend, traffic spread across backends. kubemark's HollowProxy runs the
same loop against a fake iptables.

This VirtualProxier is that loop at kubemark fidelity: informer-driven
full resyncs (syncProxyRules rebuilds everything each pass, exactly like
the reference) materializing a per-node FORWARDING TABLE
{service key -> tuple of (pod_key, node_name) backends}, plus `route()`,
the userspace-proxy-style round-robin backend pick standing in for the
iptables statistic-random chain. The pruned model has no pod IPs; the
(pod_key, node) pair is the routable identity, matching the Endpoints
encoding (api/types.py Endpoints)."""
from __future__ import annotations

import threading
from typing import Optional

from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.store import Store, SERVICES, ENDPOINTS


class VirtualProxier:
    def __init__(self, store: Store, node_name: str):
        self.store = store
        self.node_name = node_name
        self.informers = InformerFactory(store)
        self._lock = threading.Lock()
        self._rules: dict[str, tuple[tuple[str, str], ...]] = {}
        self._rr: dict[str, int] = {}          # per-service round-robin cursor
        self.sync_count = 0
        self._pending = True
        # any Service/Endpoints event schedules a full resync — the
        # reference coalesces bursts the same way (async runner); rules are
        # rebuilt from the informer caches, never patched incrementally
        mark = lambda *_: setattr(self, "_pending", True)
        for kind in (SERVICES, ENDPOINTS):
            self.informers.informer(kind).add_event_handler(
                on_add=mark, on_update=mark, on_delete=mark)

    # -- lifecycle -----------------------------------------------------------
    def sync(self) -> None:
        self.informers.sync_all()
        self._sync_rules()

    def pump(self) -> int:
        n = self.informers.pump_all()
        if self._pending:
            self._sync_rules()
        return n

    def _sync_rules(self) -> None:
        """syncProxyRules: rebuild the whole table from the caches."""
        eps = {e.key: e for e in self.informers.informer(ENDPOINTS).list()}
        rules: dict[str, tuple[tuple[str, str], ...]] = {}
        for svc in self.informers.informer(SERVICES).list():
            e = eps.get(svc.key)
            rules[svc.key] = tuple(e.addresses) if e is not None else ()
        with self._lock:
            self._rules = rules
            self._rr = {k: v for k, v in self._rr.items() if k in rules}
        self.sync_count += 1
        self._pending = False

    # -- the dataplane surface ----------------------------------------------
    def backends(self, service_key: str) -> tuple[tuple[str, str], ...]:
        with self._lock:
            return self._rules.get(service_key, ())

    def rules(self) -> dict[str, tuple[tuple[str, str], ...]]:
        with self._lock:
            return dict(self._rules)

    def route(self, service_key: str) -> Optional[tuple[str, str]]:
        """One virtual connection: pick the next backend round-robin (the
        deterministic stand-in for the iptables statistic-random chain;
        the userspace proxier's LoadBalancerRR works exactly so). None =
        no endpoints (the reference REJECTs such traffic)."""
        with self._lock:
            backends = self._rules.get(service_key, ())
            if not backends:
                return None
            i = self._rr.get(service_key, 0)
            self._rr[service_key] = i + 1
            return backends[i % len(backends)]
