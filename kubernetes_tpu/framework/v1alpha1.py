"""Plugin framework: registry, extension points, cycle context, waiting pods.

Mirrors pkg/scheduler/framework/v1alpha1/:
- Status/Code (interface.go:31-91)
- extension points of this API version: QueueSort (:123), Reserve (:135),
  Prebind (:144), Unreserve (:155), Permit (:164 — wait with timeout)
- Framework assembly from a Registry (framework.go:52: instantiate every
  registered plugin, type-assert into per-point slices)
- PluginContext (context.go:39): cycle-scoped KV store
- waitingPodsMap (waiting_pods_map.go:27)

Plus the Filter/Score points the north star assumes (added in later
reference versions; here they bridge to the predicate/priority tables and
the TPU kernels).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

# -- Status codes (interface.go:41-57) ---------------------------------------
SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
WAIT = 3


class Status:
    def __init__(self, code: int = SUCCESS, message: str = ""):
        self.code = code
        self.message = message

    @staticmethod
    def success() -> "Status":
        return Status(SUCCESS)

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def __repr__(self):
        names = {SUCCESS: "Success", ERROR: "Error",
                 UNSCHEDULABLE: "Unschedulable", WAIT: "Wait"}
        return f"Status({names.get(self.code, self.code)}, {self.message!r})"


class PluginContext:
    """Cycle-scoped thread-safe KV store (context.go:39)."""

    def __init__(self):
        self._data: dict[str, Any] = {}
        self._lock = threading.RLock()

    def read(self, key: str) -> Any:
        with self._lock:
            if key not in self._data:
                raise KeyError(key)
            return self._data[key]

    def write(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)


# -- plugin interfaces --------------------------------------------------------
class Plugin:
    NAME = "unnamed"

    def name(self) -> str:
        return self.NAME


class QueueSortPlugin(Plugin):
    def less(self, pod_info1, pod_info2) -> bool:
        raise NotImplementedError


class ReservePlugin(Plugin):
    def reserve(self, ctx: PluginContext, pod, node_name: str) -> Status:
        raise NotImplementedError


class PrebindPlugin(Plugin):
    def prebind(self, ctx: PluginContext, pod, node_name: str) -> Status:
        raise NotImplementedError


class UnreservePlugin(Plugin):
    def unreserve(self, ctx: PluginContext, pod, node_name: str) -> None:
        raise NotImplementedError


class PermitPlugin(Plugin):
    def permit(self, ctx: PluginContext, pod, node_name: str
               ) -> tuple[Status, float]:
        """Returns (status, timeout_seconds); WAIT parks the pod."""
        raise NotImplementedError


class WaitingPod:
    """A pod parked at Permit (waiting_pods_map.go)."""

    def __init__(self, pod, timeout: float):
        self.pod = pod
        self.timeout = timeout
        self._event = threading.Event()
        self._allowed = False

    def allow(self) -> None:
        self._allowed = True
        self._event.set()

    def reject(self) -> None:
        self._allowed = False
        self._event.set()

    def wait(self) -> bool:
        """Block until allowed/rejected/timeout. True = allowed."""
        signaled = self._event.wait(self.timeout)
        return self._allowed if signaled else False


# -- registry + framework -----------------------------------------------------
PluginFactory = Callable[[dict, "FrameworkHandle"], Plugin]


class Registry(dict):
    """name -> PluginFactory (registry.go:31)."""

    def register(self, name: str, factory: PluginFactory) -> None:
        if name in self:
            raise ValueError(f"plugin {name} already registered")
        self[name] = factory

    def unregister(self, name: str) -> None:
        if name not in self:
            raise ValueError(f"plugin {name} not registered")
        del self[name]

    def merge(self, other: "Registry") -> None:
        for name, factory in other.items():
            self.register(name, factory)


class FrameworkHandle:
    """What plugins may touch (interface.go:210): the cycle snapshot and the
    API surface."""

    def __init__(self, snapshot_fn: Callable[[], dict], store=None):
        self._snapshot_fn = snapshot_fn
        self.store = store

    def node_info_snapshot(self) -> dict:
        return self._snapshot_fn()


class Framework(FrameworkHandle):
    """Instantiates every registered plugin and dispatches per point
    (framework.go:52-90)."""

    def __init__(self, registry: Registry, plugin_args: Optional[dict] = None,
                 snapshot_fn: Callable[[], dict] = lambda: {}, store=None,
                 enabled: Optional[list[str]] = None):
        super().__init__(snapshot_fn, store)
        self.plugins: dict[str, Plugin] = {}
        self.queue_sort: list[QueueSortPlugin] = []
        self.reserve: list[ReservePlugin] = []
        self.prebind: list[PrebindPlugin] = []
        self.unreserve: list[UnreservePlugin] = []
        self.permit: list[PermitPlugin] = []
        self.waiting_pods: dict[str, WaitingPod] = {}
        self._waiting_lock = threading.RLock()
        args = plugin_args or {}
        names = enabled if enabled is not None else list(registry)
        for name in names:
            factory = registry.get(name)
            if factory is None:
                raise ValueError(f"plugin {name} not in registry")
            p = factory(args.get(name, {}), self)
            self.plugins[name] = p
            if isinstance(p, QueueSortPlugin):
                self.queue_sort.append(p)
            if isinstance(p, ReservePlugin):
                self.reserve.append(p)
            if isinstance(p, PrebindPlugin):
                self.prebind.append(p)
            if isinstance(p, UnreservePlugin):
                self.unreserve.append(p)
            if isinstance(p, PermitPlugin):
                self.permit.append(p)
        if len(self.queue_sort) > 1:
            raise ValueError("only one QueueSort plugin may be enabled")

    # -- dispatch (framework.go RunXPlugins) ---------------------------------
    def run_reserve_plugins(self, ctx: PluginContext, pod, node_name: str) -> Status:
        for p in self.reserve:
            st = p.reserve(ctx, pod, node_name)
            if not st.is_success():
                return Status(ERROR, f"reserve plugin {p.name()}: {st.message}")
        return Status.success()

    def run_prebind_plugins(self, ctx: PluginContext, pod, node_name: str) -> Status:
        for p in self.prebind:
            st = p.prebind(ctx, pod, node_name)
            if not st.is_success():
                if st.code == UNSCHEDULABLE:
                    return st
                return Status(ERROR, f"prebind plugin {p.name()}: {st.message}")
        return Status.success()

    def run_unreserve_plugins(self, ctx: PluginContext, pod, node_name: str) -> None:
        for p in self.unreserve:
            p.unreserve(ctx, pod, node_name)

    def run_permit_plugins(self, ctx: PluginContext, pod, node_name: str) -> Status:
        """Runs permits; on WAIT parks the pod and blocks until
        allow/reject/timeout (framework.go RunPermitPlugins + WaitOnPermit)."""
        timeout = 0.0
        status_code = SUCCESS
        for p in self.permit:
            st, t = p.permit(ctx, pod, node_name)
            if not st.is_success():
                if st.code == UNSCHEDULABLE:
                    return st
                if st.code == WAIT:
                    status_code = WAIT
                    timeout = max(timeout, t)
                else:
                    return Status(ERROR, f"permit plugin {p.name()}: {st.message}")
        if status_code != WAIT:
            return Status.success()
        wp = WaitingPod(pod, timeout)
        with self._waiting_lock:
            self.waiting_pods[pod.uid] = wp
        try:
            allowed = wp.wait()
        finally:
            with self._waiting_lock:
                self.waiting_pods.pop(pod.uid, None)
        if allowed:
            return Status.success()
        return Status(UNSCHEDULABLE, f"pod {pod.key} rejected while waiting at permit")

    def get_waiting_pod(self, uid: str) -> Optional[WaitingPod]:
        with self._waiting_lock:
            return self.waiting_pods.get(uid)

    def iterate_waiting_pods(self) -> list[WaitingPod]:
        with self._waiting_lock:
            return list(self.waiting_pods.values())
