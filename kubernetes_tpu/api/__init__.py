from kubernetes_tpu.api.types import *  # noqa: F401,F403
from kubernetes_tpu.api import quantity  # noqa: F401
