"""Pruned Kubernetes API data model — the subset the scheduler reads.

Mirrors the semantics (not the code) of the reference's `k8s.io/api/core/v1`
types as consumed by `pkg/scheduler` (reference: pkg/scheduler/nodeinfo/
node_info.go:47,139; pkg/apis/core/types.go). Quantities are plain integers:
CPU in milli-cores, memory/ephemeral-storage in bytes, scalar (extended)
resources in their native integer unit.
"""
from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass, field
from typing import Iterable, Optional

# ---------------------------------------------------------------------------
# Resource names (reference: k8s.io/api/core/v1/types.go ResourceName)
# ---------------------------------------------------------------------------
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"

# Default requests applied by priorities (NOT predicates) when a pod does not
# specify them (reference: algorithm/priorities/util/non_zero.go:31-34).
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

# Zone/region well-known labels (reference: k8s.io/api/core/v1/well_known_labels.go)
LABEL_ZONE_FAILURE_DOMAIN = "failure-domain.beta.kubernetes.io/zone"
LABEL_ZONE_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_HOSTNAME = "kubernetes.io/hostname"

# Taint applied for `node.Spec.Unschedulable` (reference: pkg/scheduler/api/well_known_labels.go)
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"


def is_extended_resource_name(name: str) -> bool:
    """Reference: k8s.io/api/core/v1/helper.IsExtendedResourceName — any
    resource not in the default kubernetes.io namespace and not a native one."""
    if name in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE, RESOURCE_PODS):
        return False
    if name.startswith("requests."):
        return False
    return "/" in name and not name.startswith("kubernetes.io/")


# ---------------------------------------------------------------------------
# Label selectors
# ---------------------------------------------------------------------------
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass(frozen=True)
class Requirement:
    """One match expression: node-selector ops include Gt/Lt; label-selector
    ops are In/NotIn/Exists/DoesNotExist."""
    key: str
    op: str
    values: tuple[str, ...] = ()

    def matches(self, labels: dict[str, str]) -> bool:
        has = self.key in labels
        val = labels.get(self.key)
        if self.op == IN:
            return has and val in self.values
        if self.op == NOT_IN:
            # Reference labels.Requirement: NotIn also matches when key absent.
            return not has or val not in self.values
        if self.op == EXISTS:
            return has
        if self.op == DOES_NOT_EXIST:
            return not has
        if self.op in (GT, LT):
            # Reference: both label value and requirement value must parse as
            # integers; non-parse → no match.
            if not has:
                return False
            try:
                lv = int(val)
                rv = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lv > rv if self.op == GT else lv < rv
        raise ValueError(f"unknown selector op {self.op!r}")


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: match_labels AND match_expressions. A None
    selector matches nothing; an empty selector matches everything
    (reference: apimachinery LabelSelectorAsSelector)."""
    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[Requirement, ...] = ()

    @staticmethod
    def from_dict(match_labels: dict[str, str] | None = None,
                  match_expressions: Iterable[Requirement] = ()) -> "LabelSelector":
        return LabelSelector(
            match_labels=tuple(sorted((match_labels or {}).items())),
            match_expressions=tuple(match_expressions),
        )

    def matches(self, labels: dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        return all(r.matches(labels) for r in self.match_expressions)


@dataclass(frozen=True)
class NodeSelectorTerm:
    """Terms are ORed; requirements within a term are ANDed. An empty term
    (no requirements) matches nothing (reference: predicates.go:889 comments)."""
    match_expressions: tuple[Requirement, ...] = ()

    def matches(self, labels: dict[str, str]) -> bool:
        if not self.match_expressions:
            return False
        return all(r.matches(labels) for r in self.match_expressions)


def node_selector_terms_match(terms: Iterable[NodeSelectorTerm], labels: dict[str, str]) -> bool:
    """ORed terms; empty list matches nothing (reference: predicates.go:833-838)."""
    return any(t.matches(labels) for t in terms)


# ---------------------------------------------------------------------------
# Affinity
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int  # 1-100
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    # None → matches all nodes; empty tuple → matches no node.
    required: Optional[tuple[NodeSelectorTerm, ...]] = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class PodAffinityTerm:
    label_selector: Optional[LabelSelector]
    topology_key: str
    namespaces: tuple[str, ...] = ()  # empty → pod's own namespace


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int  # 1-100
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


def has_pod_affinity_terms(pod) -> bool:
    """True when the pod carries any inter-pod (anti-)affinity terms — the
    predicate behind NodeInfo.pods_with_affinity and the queue's
    assigned-pod wake-up filter."""
    a = pod.affinity
    return a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None)


# ---------------------------------------------------------------------------
# Taints & tolerations
# ---------------------------------------------------------------------------
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key with Exists → tolerates everything
    op: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty → matches all effects
    # None → tolerate forever; N → evictable N seconds after the NoExecute
    # taint lands (read by the node-lifecycle taint manager)
    toleration_seconds: Optional[float] = None

    def tolerates(self, taint: Taint) -> bool:
        """Reference: k8s.io/api/core/v1/toleration.go ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.op in (TOLERATION_OP_EXISTS, ""):
            # "" defaults to Equal in the API but Exists when key is empty;
            # we normalize: empty key + any op tolerates all keys only with Exists.
            if self.op == TOLERATION_OP_EXISTS:
                return True
            return self.value == taint.value
        if self.op == TOLERATION_OP_EQUAL:
            return self.value == taint.value
        return False


def tolerations_tolerate_taint(tolerations: Iterable[Toleration], taint: Taint) -> bool:
    return any(t.tolerates(taint) for t in tolerations)


def find_intolerable_taint(taints: Iterable[Taint], tolerations: Iterable[Toleration],
                           effect_filter) -> Optional[Taint]:
    """Reference: v1helper.TolerationsTolerateTaintsWithFilter — first
    filtered taint not tolerated, else None."""
    for taint in taints:
        if not effect_filter(taint):
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            return taint
    return None


# ---------------------------------------------------------------------------
# Containers & pods
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass(frozen=True)
class Container:
    name: str = ""
    image: str = ""
    # resource requests/limits; missing keys mean "not specified"
    requests: tuple[tuple[str, int], ...] = ()
    limits: tuple[tuple[str, int], ...] = ()
    ports: tuple[ContainerPort, ...] = ()

    @staticmethod
    def make(name: str = "", image: str = "",
             requests: dict[str, int] | None = None,
             limits: dict[str, int] | None = None,
             ports: Iterable[ContainerPort] = ()) -> "Container":
        return Container(name=name, image=image,
                         requests=tuple(sorted((requests or {}).items())),
                         limits=tuple(sorted((limits or {}).items())),
                         ports=tuple(ports))

    def requests_dict(self) -> dict[str, int]:
        return dict(self.requests)

    def limits_dict(self) -> dict[str, int]:
        return dict(self.limits)


# ---------------------------------------------------------------------------
# Volumes (pruned: the scheduler-relevant subset of v1.Volume / PV / PVC)
# ---------------------------------------------------------------------------
# volume plugins with per-node attach limits (predicates.go Max*VolumeCount)
PLUGIN_EBS = "ebs"
PLUGIN_GCE_PD = "gce-pd"
PLUGIN_AZURE_DISK = "azure-disk"
PLUGIN_CINDER = "cinder"
PLUGIN_CSI = "csi"

# reference defaults (volumeutil Default*VolumeLimit)
DEFAULT_VOLUME_LIMITS = {
    PLUGIN_EBS: 39,
    PLUGIN_GCE_PD: 16,
    PLUGIN_AZURE_DISK: 16,
    PLUGIN_CINDER: 256,
}


@dataclass(frozen=True)
class VolumeSource:
    """Pruned v1.Volume: either a direct backing volume (plugin + id) or a
    PVC reference."""
    name: str
    pvc: str = ""            # persistentVolumeClaim.claimName (same namespace)
    plugin: str = ""         # direct volume plugin (PLUGIN_*)
    volume_id: str = ""      # backing volume id for direct volumes
    read_only: bool = False


@dataclass
class PersistentVolume:
    """Pruned v1.PersistentVolume."""
    name: str
    plugin: str = ""
    volume_id: str = ""
    capacity: int = 0                       # bytes
    labels: dict[str, str] = field(default_factory=dict)  # zone/region labels
    storage_class: str = ""
    claim_ref: str = ""                     # "namespace/name" when bound
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "PersistentVolume":
        out = _shallow(self)
        out.labels = dict(self.labels)
        return out


@dataclass
class PersistentVolumeClaim:
    """Pruned v1.PersistentVolumeClaim."""
    name: str
    namespace: str = "default"
    request: int = 0                        # bytes
    storage_class: str = ""
    volume_name: str = ""                   # bound PV name
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "PersistentVolumeClaim":
        return _shallow(self)


def _shallow(obj):
    """Shallow copy skipping the copy protocol (__reduce_ex__/_reconstruct
    costs ~4x a plain dict copy, and clone() sits on the store's per-write
    hot path)."""
    cls = obj.__class__
    out = cls.__new__(cls)
    out.__dict__.update(obj.__dict__)
    return out


_pod_uid_counter = itertools.count(1)


@dataclass
class Pod:
    """Pruned v1.Pod: metadata + the spec/status fields the scheduler reads."""
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    # spec
    node_name: str = ""          # spec.nodeName (set by binding)
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: tuple[Toleration, ...] = ()
    containers: tuple[Container, ...] = ()
    init_containers: tuple[Container, ...] = ()
    priority: int = 0            # resolved PriorityClass value
    priority_class_name: str = ""   # resolved by the priority admission plugin
    scheduler_name: str = "default-scheduler"
    # defaulted to "default" by the serviceaccount admission plugin
    service_account_name: str = ""
    volumes: tuple[VolumeSource, ...] = ()
    # status
    nominated_node_name: str = ""
    phase: str = "Pending"
    conditions: tuple["PodCondition", ...] = ()
    start_time: Optional[float] = None
    # controller owner reference (kind, name, uid) — read by
    # NodePreferAvoidPods priority and selector-spread listers
    owner_ref: Optional[tuple[str, str, str]] = None
    # bookkeeping
    resource_version: int = 0
    creation_timestamp: float = 0.0
    deleted: bool = False

    def __post_init__(self):
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}/{next(_pod_uid_counter)}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "Pod":
        """Fast copy: nested spec structures are frozen dataclasses and are
        shared; only the mutable dicts and top-level fields are fresh. The
        store uses this on every read/write (the serialize boundary)."""
        out = _shallow(self)
        out.labels = dict(self.labels)
        out.node_selector = dict(self.node_selector)
        return out


@dataclass(frozen=True)
class PodCondition:
    """Pruned v1.PodCondition (the scheduler writes PodScheduled=False with
    a reason/message on failure; reference: factory.go:715-726)."""
    type: str       # "PodScheduled", ...
    status: str     # "True" / "False" / "Unknown"
    reason: str = ""
    message: str = ""


POD_SCHEDULED = "PodScheduled"
CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
# condition/event reasons (reference: v1.PodReasonUnschedulable,
# core/generic_scheduler.go SchedulerError usage in scheduler.go:350)
REASON_UNSCHEDULABLE = "Unschedulable"
REASON_SCHEDULER_ERROR = "SchedulerError"


@dataclass
class EventRecord:
    """Pruned v1.Event: the user-visible audit record the scheduler emits
    (reference: record.EventRecorder calls, scheduler.go:268,325,433).
    Aggregated by (object, reason, message) with a count like the
    reference's event correlator."""
    name: str
    involved_kind: str          # "Pod", ...
    involved_key: str           # namespace/name of the object
    type: str                   # "Normal" / "Warning"
    reason: str                 # "Scheduled", "FailedScheduling", "Preempted"
    message: str = ""
    count: int = 1
    namespace: str = "default"
    component: str = ""         # emitting component (v1.EventSource.Component)
    # bookkeeping
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "EventRecord":
        return _shallow(self)


@dataclass(frozen=True)
class ImageState:
    names: tuple[str, ...]
    size_bytes: int


@dataclass(frozen=True)
class NodeCondition:
    type: str       # Ready, MemoryPressure, DiskPressure, PIDPressure, ...
    status: str     # "True" / "False" / "Unknown"


@dataclass
class Node:
    """Pruned v1.Node."""
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    # spec
    taints: tuple[Taint, ...] = ()
    unschedulable: bool = False
    pod_cidr: str = ""        # allocated by controllers.nodeipam
    # scheduler.alpha.kubernetes.io/preferAvoidPods annotation, reduced to
    # the controller UIDs it names (reference: node_prefer_avoid_pods.go)
    prefer_avoid_pod_uids: tuple[str, ...] = ()
    # status
    allocatable: dict[str, int] = field(default_factory=dict)  # cpu(milli), memory(bytes), pods, ephemeral-storage, scalar
    images: tuple[ImageState, ...] = ()
    conditions: tuple[NodeCondition, ...] = ()
    # bookkeeping
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "Node":
        out = _shallow(self)
        out.labels = dict(self.labels)
        out.annotations = dict(self.annotations)
        out.allocatable = dict(self.allocatable)
        return out


def get_zone_key(node: Node) -> str:
    """Reference: pkg/util/node.GetZoneKey — region+":\\x00:"+zone from the
    failure-domain labels; empty string when both are empty."""
    region = node.labels.get(LABEL_ZONE_REGION, "")
    zone = node.labels.get(LABEL_ZONE_FAILURE_DOMAIN, "")
    if region == "" and zone == "":
        return ""
    return region + ":\x00:" + zone


# ---------------------------------------------------------------------------
# Workload objects used by SelectorSpread (services / RCs / RSs / STSs)
# ---------------------------------------------------------------------------
@dataclass
class Service:
    name: str
    namespace: str = "default"
    selector: dict[str, str] = field(default_factory=dict)  # empty → selects nothing
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class PodTemplate:
    """Pruned v1.PodTemplateSpec — the pod shape workload controllers stamp
    out (reference: pkg/apis/core/types.go PodTemplateSpec as embedded in
    apps/batch workload specs)."""
    labels: dict[str, str] = field(default_factory=dict)
    containers: tuple[Container, ...] = ()
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: tuple[Toleration, ...] = ()
    affinity: Optional[Affinity] = None
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"

    def make_pod(self, name: str, namespace: str,
                 owner_ref: Optional[tuple[str, str, str]] = None,
                 extra_labels: Optional[dict[str, str]] = None,
                 node_name: str = "") -> Pod:
        labels = dict(self.labels)
        if extra_labels:
            labels.update(extra_labels)
        return Pod(
            name=name, namespace=namespace, labels=labels,
            containers=self.containers or (Container.make(name="c"),),
            node_selector=dict(self.node_selector),
            tolerations=self.tolerations, affinity=self.affinity,
            priority_class_name=self.priority_class_name,
            scheduler_name=self.scheduler_name,
            node_name=node_name, owner_ref=owner_ref)


@dataclass
class ReplicaSet:
    """Pruned apps/v1.ReplicaSet (also stands in for RC). `template` drives
    the pods the controller stamps out; None keeps the legacy
    selector-labels-only shape (reference: pkg/apis/apps/types.go
    ReplicaSetSpec)."""
    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    replicas: int = 1            # spec.replicas (PDB expected-scale source)
    template: Optional[PodTemplate] = None
    # set by the deployment controller on rollout-owned sets
    owner_ref: Optional[tuple[str, str, str]] = None
    # status (reconciled by controllers.replicaset)
    observed_replicas: int = 0
    ready_replicas: int = 0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Deployment:
    """Pruned apps/v1.Deployment: declarative rollout over owned
    ReplicaSets (reference: pkg/apis/apps/types.go DeploymentSpec;
    controller pkg/controller/deployment)."""
    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: Optional[PodTemplate] = None
    strategy: str = "RollingUpdate"        # RollingUpdate | Recreate
    max_surge: int = 1                     # rolling: extra pods allowed
    max_unavailable: int = 1               # rolling: pods that may be down
    paused: bool = False
    # status
    observed_revision: str = ""            # template hash of the newest RS
    updated_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Job:
    """Pruned batch/v1.Job: run-to-completion workload
    (reference: pkg/apis/batch/types.go JobSpec; controller
    pkg/controller/job)."""
    name: str
    namespace: str = "default"
    template: Optional[PodTemplate] = None
    completions: int = 1
    parallelism: int = 1
    backoff_limit: int = 6
    ttl_seconds_after_finished: Optional[float] = None
    # controller owner reference (kind, name, uid) — the CronJob controller
    # claims its Jobs through this, like pods carry owner_ref; the typed
    # tuple matters: serde rebuilds tuple[str, str, str] from JSON lists
    owner_ref: Optional[tuple[str, str, str]] = None
    # status
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    complete: bool = False
    job_failed: bool = False               # backoff limit exceeded
    completion_time: Optional[float] = None
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class DaemonSet:
    """Pruned apps/v1.DaemonSet. In the reference snapshot the DS controller
    schedules its own pods (sets nodeName directly,
    pkg/controller/daemon/daemon_controller.go:81) — mirrored here."""
    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplate] = None
    # status
    desired_number_scheduled: int = 0
    current_number_scheduled: int = 0
    number_ready: int = 0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class StatefulSet:
    """Pruned apps/v1.StatefulSet: stable ordinal identities name-0..N-1,
    OrderedReady scale-up/down (reference: pkg/apis/apps/types.go
    StatefulSetSpec; controller pkg/controller/statefulset)."""
    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    template: Optional[PodTemplate] = None
    replicas: int = 1
    service_name: str = ""
    pod_management_policy: str = "OrderedReady"   # | Parallel
    # status
    current_replicas: int = 0
    ready_replicas: int = 0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class HorizontalPodAutoscaler:
    """Pruned autoscaling/v1.HorizontalPodAutoscaler (reference:
    pkg/apis/autoscaling/types.go; controller
    pkg/controller/podautoscaler/horizontal.go): CPU-utilization-driven
    scaling of a workload's replica count."""
    name: str
    namespace: str = "default"
    # scaleTargetRef — (kind, name); Deployment is the supported target
    scale_target_ref: tuple[str, str] = ("Deployment", "")
    min_replicas: int = 1
    max_replicas: int = 10
    # targetCPUUtilizationPercentage: desired avg usage / request percent
    target_cpu_utilization: int = 80
    # status
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization: Optional[int] = None
    last_scale_time: Optional[float] = None
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class PodMetrics:
    """metrics.k8s.io PodMetrics stand-in (the metrics-server feed the HPA
    reads): per-pod CPU usage in millicores, keyed like the pod."""
    name: str
    namespace: str = "default"
    cpu_usage: int = 0                     # millicores
    window: float = 30.0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class CronJob:
    """Pruned batch/v1beta1.CronJob (reference: pkg/apis/batch/types.go;
    controller pkg/controller/cronjob/cronjob_controller.go): creates Jobs
    on a 5-field cron schedule."""
    name: str
    namespace: str = "default"
    schedule: str = "* * * * *"
    template: Optional[PodTemplate] = None
    completions: int = 1
    parallelism: int = 1
    suspend: bool = False
    # Allow | Forbid | Replace (cronjob_controller.go concurrencyPolicy)
    concurrency_policy: str = "Allow"
    starting_deadline_seconds: Optional[float] = None
    # status
    last_schedule_time: Optional[float] = None
    creation_time: Optional[float] = None
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Namespace:
    """Pruned v1.Namespace (cluster-scoped). DELETE moves it to Terminating;
    the namespace controller empties it then removes it (reference:
    pkg/controller/namespace finalization). `annotations` carries the
    scheduler.alpha.kubernetes.io/{defaultTolerations,tolerationsWhitelist}
    JSON the podtolerationrestriction admission plugin reads."""
    name: str
    phase: str = "Active"                  # Active | Terminating
    annotations: dict[str, str] = field(default_factory=dict)
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name


@dataclass
class ConfigMap:
    name: str
    namespace: str = "default"
    data: dict[str, str] = field(default_factory=dict)
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Secret:
    name: str
    namespace: str = "default"
    type: str = "Opaque"
    data: dict[str, str] = field(default_factory=dict)   # base64 by convention
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ServiceAccount:
    name: str
    namespace: str = "default"
    secrets: tuple[str, ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class PodDisruptionBudget:
    name: str
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    # spec: exactly one of min_available / max_unavailable; int or "N%"
    # (policy/v1beta1 PodDisruptionBudgetSpec). Both None = no reconcile
    # (tests that pin disruptions_allowed literals keep working).
    min_available: Optional[object] = None
    max_unavailable: Optional[object] = None
    # status (reconciled by controllers.disruption from pod state)
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease, pruned: one record serves BOTH the
    leader-election resourcelock (LeaderElectionRecord analog — `holder`,
    transitions) and the node heartbeat (NodeLease, kubelet
    nodelease.NewController): a node's kubelet renews `node-<name>` every
    lease interval, and the node-lifecycle controller grades Ready→Unknown
    from renew_time staleness instead of polling status fields."""
    name: str
    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration: float = 15.0
    leader_transitions: int = 0
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "Lease":
        return copy.copy(self)


def node_lease_key(node_name: str) -> str:
    """The per-node heartbeat Lease key (kube-node-lease namespace analog;
    shared by the hollow kubelet's renewer and the health monitor)."""
    return f"node-{node_name}"


@dataclass
class Endpoints:
    """Pruned v1.Endpoints — one subset: the ready backends of a Service.
    Addresses are (pod_key, node_name) pairs (no pod IPs exist in this
    model; the key is the routable identity). Reconciled by
    controllers.endpoints from the service selector."""
    name: str
    namespace: str = "default"
    addresses: tuple[tuple[str, str], ...] = ()
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "Endpoints":
        return _shallow(self)


@dataclass
class ResourceQuota:
    """Pruned v1.ResourceQuota: per-namespace hard caps on aggregate pod
    requests and object counts. `hard` / `used` map resource names
    ("cpu" milli, "memory" bytes, "pods") to totals; `used` is reconciled
    by controllers.resourcequota and enforced at admission
    (plugin/pkg/admission/resourcequota)."""
    name: str
    namespace: str = "default"
    hard: dict[str, int] = field(default_factory=dict)
    used: dict[str, int] = field(default_factory=dict)
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "ResourceQuota":
        out = _shallow(self)
        out.hard = dict(self.hard)
        out.used = dict(self.used)
        return out


@dataclass
class PriorityClass:
    """Pruned scheduling.k8s.io/v1beta1 PriorityClass — resolved into
    pod.priority by the priority admission plugin
    (plugin/pkg/admission/priority; the scheduler reads the resolved value
    via util.GetPodPriority)."""
    name: str
    value: int = 0
    global_default: bool = False
    description: str = ""
    resource_version: int = 0

    @property
    def key(self) -> str:
        return self.name

    def clone(self) -> "PriorityClass":
        return _shallow(self)


# ---------------------------------------------------------------------------
# Resource aggregate (reference: nodeinfo.Resource, node_info.go:139)
# ---------------------------------------------------------------------------
@dataclass
class ResourceAgg:
    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar: dict[str, int] = field(default_factory=dict)

    @staticmethod
    def from_allocatable(alloc: dict[str, int]) -> "ResourceAgg":
        r = ResourceAgg()
        for name, q in alloc.items():
            if name == RESOURCE_CPU:
                r.milli_cpu = q
            elif name == RESOURCE_MEMORY:
                r.memory = q
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                r.ephemeral_storage = q
            elif name == RESOURCE_PODS:
                r.allowed_pod_number = q
            else:
                r.scalar[name] = q
        return r

    def add_requests(self, requests: dict[str, int]) -> None:
        for name, q in requests.items():
            if name == RESOURCE_CPU:
                self.milli_cpu += q
            elif name == RESOURCE_MEMORY:
                self.memory += q
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                self.ephemeral_storage += q
            elif name != RESOURCE_PODS:
                self.scalar[name] = self.scalar.get(name, 0) + q

    def set_max(self, requests: dict[str, int]) -> None:
        """Reference: Resource.SetMaxResource — elementwise max (for init containers)."""
        for name, q in requests.items():
            if name == RESOURCE_CPU:
                self.milli_cpu = max(self.milli_cpu, q)
            elif name == RESOURCE_MEMORY:
                self.memory = max(self.memory, q)
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                self.ephemeral_storage = max(self.ephemeral_storage, q)
            elif name != RESOURCE_PODS:
                self.scalar[name] = max(self.scalar.get(name, 0), q)

    def clone(self) -> "ResourceAgg":
        return ResourceAgg(self.milli_cpu, self.memory, self.ephemeral_storage,
                           self.allowed_pod_number, dict(self.scalar))


def get_resource_request(pod: Pod) -> ResourceAgg:
    """Reference: predicates.GetResourceRequest (predicates.go:743) —
    sum over containers, then elementwise max with each init container."""
    r = ResourceAgg()
    for c in pod.containers:
        r.add_requests(c.requests_dict())
    for c in pod.init_containers:
        r.set_max(c.requests_dict())
    return r


def get_resource_limits(pod: Pod) -> ResourceAgg:
    """Reference: priorities/resource_limits.go:93 getResourceLimits — sum
    container limits, then elementwise max with each init container."""
    r = ResourceAgg()
    for c in pod.containers:
        r.add_requests(c.limits_dict())
    for c in pod.init_containers:
        r.set_max(c.limits_dict())
    return r


def get_nonzero_requests(requests: dict[str, int]) -> tuple[int, int]:
    """Reference: priorities/util/non_zero.go:38 — default 100m CPU / 200MB
    memory when *unset* (explicit zero stays zero)."""
    cpu = requests[RESOURCE_CPU] if RESOURCE_CPU in requests else DEFAULT_MILLI_CPU_REQUEST
    mem = requests[RESOURCE_MEMORY] if RESOURCE_MEMORY in requests else DEFAULT_MEMORY_REQUEST
    return cpu, mem


def get_pod_nonzero_requests(pod: Pod) -> tuple[int, int]:
    """Reference: priorities/resource_allocation.go:97 getNonZeroRequests —
    per-container defaulted sums (init containers are NOT considered)."""
    cpu = mem = 0
    for c in pod.containers:
        ccpu, cmem = get_nonzero_requests(c.requests_dict())
        cpu += ccpu
        mem += cmem
    return cpu, mem


def get_container_ports(*pods: Pod) -> list[ContainerPort]:
    """Reference: pkg/scheduler/util.GetContainerPorts — ports with HostPort>0."""
    out = []
    for pod in pods:
        for c in pod.containers:
            for p in c.ports:
                if p.host_port > 0:
                    out.append(p)
    return out
