"""JSON serialization for the API types — the runtime.Scheme analog.

The reference's apimachinery gives every object a serialize/deserialize
round trip (runtime.Scheme + codecs); this provides the same contract for
the pruned dataclasses: `to_dict(obj)` -> plain JSON-able dict,
`from_dict(kind, d)` -> object, driven generically off dataclass type
hints (nested dataclasses, tuples of dataclasses, tuple-of-pairs maps,
Optionals). Used by the REST apiserver and kubectl.
"""
from __future__ import annotations

import dataclasses
import typing
from typing import Any, Optional, get_args, get_origin, get_type_hints

from kubernetes_tpu.api import types as T
from kubernetes_tpu.store import store as store_mod

# store kind -> object class (the scheme's kind registry)
KIND_TYPES = {
    store_mod.PODS: T.Pod,
    store_mod.NODES: T.Node,
    store_mod.SERVICES: T.Service,
    store_mod.REPLICASETS: T.ReplicaSet,
    store_mod.PDBS: T.PodDisruptionBudget,
    store_mod.PVS: T.PersistentVolume,
    store_mod.PVCS: T.PersistentVolumeClaim,
    store_mod.EVENTS: T.EventRecord,
    "priorityclasses": T.PriorityClass,
    store_mod.ENDPOINTS: T.Endpoints,
    store_mod.RESOURCEQUOTAS: T.ResourceQuota,
    store_mod.DEPLOYMENTS: T.Deployment,
    store_mod.JOBS: T.Job,
    store_mod.DAEMONSETS: T.DaemonSet,
    store_mod.STATEFULSETS: T.StatefulSet,
    store_mod.NAMESPACES: T.Namespace,
    store_mod.CONFIGMAPS: T.ConfigMap,
    store_mod.SECRETS: T.Secret,
    store_mod.SERVICEACCOUNTS: T.ServiceAccount,
    store_mod.HPAS: T.HorizontalPodAutoscaler,
    store_mod.PODMETRICS: T.PodMetrics,
    store_mod.CRONJOBS: T.CronJob,
}

# coordination.k8s.io/Lease — one kind serves the leader-election
# resourcelock AND the node-heartbeat NodeLease, so leader election and
# the node-lifecycle health monitor work over the remote transport too
KIND_TYPES[store_mod.LEASES] = T.Lease

# rbac.authorization.k8s.io policy objects: the store-backed authorizer
# and the clusterrole-aggregation controller read these
from kubernetes_tpu.apiserver.auth import (  # noqa: E402
    Role as _Role, RoleBinding as _RoleBinding)
KIND_TYPES[store_mod.CLUSTERROLES] = _Role
KIND_TYPES[store_mod.CLUSTERROLEBINDINGS] = _RoleBinding

# co-scheduling gangs (scheduling.sigs.k8s.io PodGroup analog): served by
# the apiserver + /status subresource, mirrored by RemoteStore
from kubernetes_tpu.coscheduling.types import PodGroup as _PodGroup  # noqa: E402
KIND_TYPES[store_mod.PODGROUPS] = _PodGroup

# kinds whose objects key by bare name (Node.key etc.); everything else
# keys by namespace/name — the single owner of REST path scoping
CLUSTER_SCOPED_KINDS = frozenset(
    kind for kind, cls in KIND_TYPES.items()
    if "namespace" not in {f.name for f in dataclasses.fields(cls)})


def to_dict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: to_dict(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    return obj


_HINTS_CACHE: dict[type, dict] = {}


def _hints(cls: type) -> dict:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        # resolve stringified annotations in the class's OWN module (types
        # registered from other modules — Lease, RBAC — name their own
        # neighbors), with api.types as fallback vocabulary
        import sys
        ns = dict(vars(T))
        ns.update(vars(sys.modules.get(cls.__module__, T)))
        h = _HINTS_CACHE[cls] = get_type_hints(cls, ns,
                                               {"Optional": Optional})
    return h


def _build(hint: Any, value: Any) -> Any:
    if value is None:
        return None
    if isinstance(hint, str):
        # a quoted forward reference nested inside a builtin generic (e.g.
        # tuple["PodCondition", ...]) survives get_type_hints as a plain
        # string — types.GenericAlias neither wraps it in ForwardRef nor
        # resolves it; look it up in the api.types vocabulary
        hint = getattr(T, hint, Any)
    origin = get_origin(hint)
    if origin is typing.Union:
        args = [a for a in get_args(hint) if a is not type(None)]
        return _build(args[0], value) if len(args) == 1 else value
    if dataclasses.is_dataclass(hint) and isinstance(value, dict):
        return from_obj_dict(hint, value)
    if origin is tuple:
        args = get_args(hint)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_build(args[0], v) for v in value)
        if args:
            return tuple(_build(a, v) for a, v in zip(args, value))
        return tuple(value)
    if origin is list:
        (elem,) = get_args(hint) or (Any,)
        return [_build(elem, v) for v in value]
    if origin is dict:
        return dict(value)
    return value


def from_obj_dict(cls: type, d: dict) -> Any:
    """Rebuild a dataclass instance from to_dict output (unknown keys are
    dropped — forward-compatible decode, like unknown-field-tolerant
    deserialization in the reference)."""
    hints = _hints(cls)
    kw = {}
    for f in dataclasses.fields(cls):
        if f.name in d:
            kw[f.name] = _build(hints.get(f.name, Any), d[f.name])
    return cls(**kw)


def from_dict(kind: str, d: dict) -> Any:
    cls = KIND_TYPES.get(kind)
    if cls is None:
        raise KeyError(f"unknown kind {kind!r}")
    return from_obj_dict(cls, d)
