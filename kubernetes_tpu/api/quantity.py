"""Minimal resource.Quantity parser — "100m" CPU, "32Gi" memory, etc.

Covers the quantity forms the scheduler benchmarks use (reference:
apimachinery/pkg/api/resource). CPU strings convert to milli-cores;
byte strings convert to bytes.
"""
from __future__ import annotations

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15}


def parse_cpu(s: str | int | float) -> int:
    """Parse a CPU quantity into milli-cores."""
    if isinstance(s, int):
        return s * 1000
    if isinstance(s, float):
        return int(s * 1000)
    s = s.strip()
    if s.endswith("m"):
        return int(s[:-1])
    return int(float(s) * 1000)


def parse_mem(s: str | int) -> int:
    """Parse a memory/storage quantity into bytes."""
    if isinstance(s, int):
        return s
    s = s.strip()
    for suf, mult in _BINARY.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult)
    for suf, mult in _DECIMAL.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult)
    return int(float(s))


def requests(cpu: str | int | float | None = None, mem: str | int | None = None,
             **scalars: int) -> dict[str, int]:
    """Build a requests dict: requests(cpu="100m", mem="200Mi", **{"example.com/foo": 2})."""
    out: dict[str, int] = {}
    if cpu is not None:
        out["cpu"] = parse_cpu(cpu)
    if mem is not None:
        out["memory"] = parse_mem(mem)
    out.update(scalars)
    return out
