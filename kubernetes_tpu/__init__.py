"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

A from-scratch re-design of Kubernetes' kube-scheduler (reference:
kubernetes @ ~v1.15.0-alpha.3) for TPU hardware: the per-cycle NodeInfo
snapshot lives as a dense struct-of-arrays matrix in HBM, and the full
Filter/Score plugin suite runs as vmapped, jitted JAX kernels over all
nodes at once, with integer-exact score parity against the reference
algorithm (see `kubernetes_tpu.oracle` for the pure-Python referee).

Layout:
  api/        pruned Pod/Node/config data model (reference: pkg/apis, pkg/scheduler/api)
  oracle/     pure-Python semantic oracle — exact reference formulas, the parity referee
  ops/        JAX kernels: encoding, device snapshot, filter/score/select
  parallel/   multi-chip sharding of the node axis (mesh, per-shard top-k, all-gather)
  framework/  plugin framework: registry, extension points, cycle context
  cache/      scheduler cache: assume/confirm/expire, generations, snapshots
  queue/      scheduling queue: activeQ / backoffQ / unschedulableQ
  store/      in-memory versioned object store with list/watch (etcd+apiserver analog)
  models/     workload & cluster models for benchmarks (scheduler_perf / kubemark analog)
  perf/       benchmark harness
  utils/      heap, clock, backoff helpers
"""

__version__ = "0.1.0"

# NOTE: jax is imported (and jax_enable_x64 switched on — reference resource
# math is int64) by `kubernetes_tpu.ops`, the first layer that touches the
# device. The api/oracle/cache/queue/store layers stay pure Python.
