"""scheduler_perf harness — density + benchmark matrix.

Mirrors test/integration/scheduler_perf:
- mustSetupScheduler (util.go:34): in-process store + scheduler, no kubelet.
- TestSchedule100Node3KPods (scheduler_test.go:68): schedule P pods over N
  hollow nodes, compute minimum observed QPS over 1s-equivalent windows;
  fail < 30 pods/s, warn < 100 (scheduler_test.go:35-38).
- BenchmarkScheduling matrices (scheduler_bench_test.go:39-131): plain /
  PodAntiAffinity / PodAffinity / NodeAffinity workloads over
  {nodes × existing pods} grids.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.types import LABEL_HOSTNAME, LABEL_ZONE_FAILURE_DOMAIN
from kubernetes_tpu.models.hollow import (
    NodeStrategy, PodStrategy, make_pods, populate_store,
)
from kubernetes_tpu.store.store import Store, EVENTS, PODS
from kubernetes_tpu.scheduler import Scheduler

MIN_QPS_THRESHOLD = 30      # scheduler_test.go:35 (fail)
WARN_QPS_THRESHOLD = 100    # scheduler_test.go:38 (warn)

# The tunneled TPU dispatches over HTTP; a dropped response surfaces as a
# JaxRuntimeError whose message carries one of these markers (the round-4
# driver bench died to "remote_compile: read body: response body closed").
# These are transport failures, not program bugs — bounded retry is correct.
# Markers are deliberately narrow multi-word phrases: a bare "unavailable"
# or "socket" would also match real validation errors (e.g. the deployment
# controller's maxUnavailable message) and silently swallow them.
TRANSIENT_ERROR_MARKERS = (
    "remote_compile", "read body", "response body closed",
    "connection reset", "connection refused", "broken pipe",
    "deadline exceeded",
)


def is_transient_error(exc: BaseException) -> bool:
    msg = str(exc).lower()
    return any(m in msg for m in TRANSIENT_ERROR_MARKERS)


def retry_transient(fn, attempts: int = 3, backoff: float = 2.0, sleep=None):
    """Run fn(); on a transient transport error retry up to `attempts` total
    tries with linear backoff. Non-transient exceptions propagate
    immediately — this must never mask a real kernel/parity bug."""
    if sleep is None:               # resolved lazily so tests can stub it
        sleep = time.sleep
    last = None
    for i in range(max(attempts, 1)):
        try:
            return fn()
        except Exception as e:        # noqa: BLE001 — filtered below
            if not is_transient_error(e):
                raise
            last = e
            if i + 1 < attempts:
                sleep(backoff * (i + 1))
    raise last


@dataclass
class PerfConfig:
    nodes: int = 100
    existing_pods: int = 0
    pods: int = 3000
    zones: int = 0
    # plain | anti-affinity | affinity | node-affinity | spread
    workload: str = "plain"
    use_tpu: bool = True
    burst: int = 1024           # 0 = serial schedule_one loop
    percentage_of_nodes_to_score: int = 100


@dataclass
class PerfResult:
    scheduled: int
    elapsed: float
    throughput: float           # pods/s over the whole run
    min_qps: float              # worst 1s-window rate (density metric)
    attempts: dict = field(default_factory=dict)

    @property
    def passes_density_threshold(self) -> bool:
        return self.min_qps >= MIN_QPS_THRESHOLD


def _pod_strategy(cfg: PerfConfig, count: int, prefix: str) -> PodStrategy:
    st = PodStrategy(count=count, name_prefix=prefix)
    if cfg.workload == "anti-affinity":
        # makeBasePodWithPodAntiAffinity: hostname topology
        # (scheduler_bench_test.go:151)
        st.anti_affinity_topology = LABEL_HOSTNAME
    elif cfg.workload == "affinity":
        # makeBasePodWithPodAffinity: ZONE topology with every node labeled
        # zone1 (scheduler_bench_test.go:175, NewLabelNodePrepareStrategy
        # :100) — co-location is per zone, so the workload never saturates a
        # single node the way a hostname topology would
        st.affinity_topology = LABEL_ZONE_FAILURE_DOMAIN
    elif cfg.workload == "node-affinity":
        st.node_affinity_key = "perf-group"
        st.node_affinity_values = ("a", "b")
    elif cfg.workload not in ("plain", "spread"):
        raise ValueError(f"unknown workload {cfg.workload!r}")
    # "spread" pods are plain-shaped; the Service created in setup() makes
    # SelectorSpreadPriority count them (selector_spreading.go:66)
    return st


def setup(cfg: PerfConfig) -> tuple[Store, Scheduler]:
    """mustSetupScheduler analog."""
    store = Store(watch_log_size=max(65536, 4 * (cfg.nodes + cfg.pods
                                                 + cfg.existing_pods)))
    node_st = NodeStrategy(count=cfg.nodes, zones=cfg.zones)
    if cfg.workload == "node-affinity":
        node_st.label_fracs = {"perf-group": ("a", 0.5)}
    elif cfg.workload == "affinity" and not cfg.zones:
        # reference: NewLabelNodePrepareStrategy(LabelZoneFailureDomain,
        # "zone1") — one zone spanning the whole cluster
        node_st.zones = 1
    elif cfg.workload == "spread" and not cfg.zones:
        # zone blend is 2/3 of the spread score (selector_spreading.go:34);
        # exercise it
        node_st.zones = 3
    # "The setup strategy creates pods with no affinity rules"
    # (scheduler_bench_test.go:68,93): existing pods are PLAIN regardless of
    # the measured workload's shape
    existing = ([PodStrategy(count=cfg.existing_pods, name_prefix="existing",
                             labels={"app": "setup"})]
                if cfg.existing_pods else [])
    populate_store(store, [node_st], existing)
    if cfg.workload == "spread":
        from kubernetes_tpu.api.types import Service
        from kubernetes_tpu.store.store import SERVICES
        store.create(SERVICES, Service(name="spread-svc",
                                       selector={"app": "density"}))
    sched = Scheduler(store, use_tpu=cfg.use_tpu,
                      percentage_of_nodes_to_score=cfg.percentage_of_nodes_to_score)
    sched.sync()
    return store, sched


def run(cfg: PerfConfig, warmup: int = 64) -> PerfResult:
    store, sched = setup(cfg)
    # warmup outside the timed window (jit compilation, informer sync)
    if warmup:
        wst = _pod_strategy(cfg, warmup, "warmup")
        if cfg.workload == "anti-affinity":
            # warmup pods must exercise the same kernels WITHOUT consuming
            # the measured workload's anti-affinity capacity: a distinct
            # label set self-anti-affines among the warmup pods only (the
            # reference sizes its cells so every measured pod fits,
            # scheduler_bench_test.go:61-66)
            wst.labels = {"app": "warmup"}
        for pod in make_pods(wst, 0):
            store.create(PODS, pod)
        sched.pump()
        _drain(sched, cfg)
        sched.pump()
    for pod in make_pods(_pod_strategy(cfg, cfg.pods, "measured"), 0):
        store.create(PODS, pod)
    sched.pump()
    before = sched.metrics.schedule_attempts["scheduled"]
    windows: list[tuple[float, int]] = []
    t0 = time.perf_counter()
    last_t, last_n = t0, before
    while True:
        n = _drain_step(sched, cfg)
        now = time.perf_counter()
        cur = sched.metrics.schedule_attempts["scheduled"]
        if now - last_t >= 1.0:
            windows.append((now - last_t, cur - last_n))
            last_t, last_n = now, cur
        if n == 0:
            break
    elapsed = time.perf_counter() - t0
    sched.pump()
    scheduled = sched.metrics.schedule_attempts["scheduled"] - before
    throughput = scheduled / elapsed if elapsed > 0 else 0.0
    if windows:
        min_qps = min(count / dt for dt, count in windows if dt > 0)
    else:
        min_qps = throughput
    return PerfResult(scheduled, elapsed, throughput, min_qps,
                      dict(sched.metrics.schedule_attempts))


def _drain_step(sched: Scheduler, cfg: PerfConfig) -> int:
    if cfg.burst:
        return sched.schedule_burst(max_pods=cfg.burst)
    return 1 if sched.schedule_one(timeout=0.0) else 0


def _drain(sched: Scheduler, cfg: PerfConfig) -> None:
    while _drain_step(sched, cfg):
        pass


def run_preempt_cell(n_nodes: int, n_victims: int,
                     n_preemptors: int = 128, mesh=None) -> dict:
    """Preemption pressure-wave cell (BASELINE configs[3]): `n_preemptors`
    failed pods run as ONE schedule-else-preempt launch on the device
    (kernels.pressure_batch) against `n_victims` lower-priority pods spread
    over `n_nodes`, vs the serial oracle doing the same work per pod (the
    reference fans selectVictimsOnNode over 16 goroutines PER pod,
    generic_scheduler.go:996). The device side runs with a WARM persistent
    victim table (TPUScheduler.prewarm_preempt) — the steady-state
    condition, since production scans ride a table maintained incrementally
    across cycles — and reports the residual per-wave encode vs device-scan
    phase split. Decisions are asserted identical before timing is
    reported; returns {scans_per_s, vs_oracle, device_seconds,
    oracle_seconds, encode_seconds, scan_seconds, preemptors}."""
    import time as _t
    from kubernetes_tpu.api.types import Pod, Node, Container
    from kubernetes_tpu.cache.node_info import NodeInfo
    from kubernetes_tpu.core.tpu_scheduler import TPUScheduler
    from kubernetes_tpu.oracle import predicates as preds
    from kubernetes_tpu.oracle.generic_scheduler import (FitError,
                                                         GenericScheduler)
    from kubernetes_tpu.oracle.preemption import Preemptor
    GI = 1024 ** 3
    per_node = max(1, n_victims // n_nodes)
    cpu_each = 4000 // per_node
    infos = {}
    names = []
    uid = 0
    for i in range(n_nodes):
        node = Node(name=f"node-{i}",
                    allocatable={"cpu": 4000, "memory": 32 * GI, "pods": 110})
        ni = NodeInfo(node)
        for _ in range(per_node):
            uid += 1
            p = Pod(name=f"victim-{uid}", priority=1, node_name=node.name,
                    containers=(Container.make(
                        name="c", requests={"cpu": cpu_each}),))
            ni.add_pod(p)
        infos[node.name] = ni
        names.append(node.name)
    preemptors = [Pod(name=f"hi-{k}", priority=10, containers=(
        Container.make(name="c", requests={"cpu": cpu_each}),))
        for k in range(n_preemptors)]

    def device_wave(tpu):
        out = tpu.preempt_pressure_burst(preemptors, infos, names, [])
        assert out is not None
        return out

    device_wave(TPUScheduler(percentage_of_nodes_to_score=100,
                             mesh=mesh))  # compile
    tpu = TPUScheduler(percentage_of_nodes_to_score=100, mesh=mesh)
    tpu.prewarm_preempt(infos, names, [])   # steady-state victim table
    t0 = _t.perf_counter()
    got = device_wave(tpu)
    dev = _t.perf_counter() - t0

    def oracle_wave():
        # the serial referee: schedule-else-preempt with nominated ghosts,
        # successes folded — normalized to the same outcome tuples the
        # device wave returns (a fit-able nodes/pods ratio must compare,
        # not crash)
        nominated: dict = {}
        nom_fn = lambda n: list(nominated.get(n, []))
        g = GenericScheduler(percentage_of_nodes_to_score=100,
                             nominated_pods_fn=nom_fn)
        world = dict(infos)
        out = []
        for pod in preemptors:
            funcs = preds.default_predicate_set(world)
            try:
                r = g.schedule(pod, world, names, predicate_funcs=funcs)
            except FitError as err:
                res = Preemptor().preempt(pod, world, names, err,
                                          nominated_pods_fn=nom_fn)
                if res.node is None:
                    out.append(("failed", not res.nominated_to_clear))
                    continue
                ghost = pod.clone()
                ghost.node_name = res.node.name
                nominated.setdefault(res.node.name, []).append(ghost)
                out.append(("nominated", res.node.name,
                            sorted(v.name for v in res.victims)))
                continue
            assumed = pod.clone()
            assumed.node_name = r.suggested_host
            ni = world[r.suggested_host].clone()
            ni.add_pod(assumed)
            world = {**world, r.suggested_host: ni}
            out.append(("bound", r.suggested_host))
        return out

    t0 = _t.perf_counter()
    want = oracle_wave()
    ora = _t.perf_counter() - t0
    norm = [("nominated", o[1], sorted(v.name for v in o[2]))
            if o[0] == "nominated" else o for o in got]
    assert norm == want, f"device/oracle preempt divergence: {norm} != {want}"
    phases = tpu.last_preempt_phases or {}
    return {
        "scans_per_s": round(n_preemptors / dev, 2),
        "vs_oracle": round(ora / dev, 2),
        "device_seconds": round(dev, 4),
        "oracle_seconds": round(ora, 4),
        "encode_seconds": round(phases.get("encode", 0.0), 4),
        "scan_seconds": round(phases.get("scan", 0.0), 4),
        "preemptors": n_preemptors,
    }


def run_shard_cell(n_nodes: int, n_pods: int = 2000, devices=None,
                   verify: bool = False, existing_per_node: int = 0) -> dict:
    """Mesh-sharded burst cell at fleet scale (50k-200k nodes) — the
    node-axis cells one chip's HBM cannot hold once the resident state is
    counted (at 200k nodes the [N_pad, P=128] victim slot planes alone are
    7 planes x 256k x 128 x 8B ~ 1.8 GiB, plus the [N_pad] node planes and
    the fused carry + checkpoint copies; PROFILE.md round-15 carries the
    arithmetic). The node axis rides NamedSharding(mesh, P("nodes")) over
    `devices` chips (default: every visible device), the burst runs the
    single-dispatch/single-fetch fused contract, and throughput counts
    decided pods.

    `verify=True` additionally reruns the identical cell single-device and
    asserts bit-identical placements — the parity referee for the scale
    cells (expensive: doubles the runtime; the fuzz suites + shard sweep
    pin parity at small N every run, so the matrix cells default to the
    sharded timing only)."""
    import time as _t
    import numpy as np
    from kubernetes_tpu.api.types import Node, Pod, Container
    from kubernetes_tpu.cache.node_info import NodeInfo
    from kubernetes_tpu.core.tpu_scheduler import TPUScheduler
    from kubernetes_tpu.parallel import sharding as S
    GI = 1024 ** 3
    infos = {}
    names = []
    for i in range(n_nodes):
        # uneven zones (n % 3 != 0 at the matrix sizes) keep the NodeTree
        # rotation machinery live at scale in callers that attach a tree
        node = Node(name=f"n{i}",
                    labels={"failure-domain.beta.kubernetes.io/zone":
                            f"z{i % 3}"},
                    allocatable={"cpu": 4000, "memory": 32 * GI,
                                 "pods": 110})
        ni = NodeInfo(node)
        for e in range(existing_per_node):
            ni.add_pod(Pod(name=f"w{i}-{e}", node_name=node.name,
                           containers=(Container.make(
                               name="c", requests={"cpu": 100}),)))
        infos[node.name] = ni
        names.append(node.name)

    def mk_pods(tag: str, count: int):
        return [Pod(name=f"{tag}{j}", labels={"app": "shard"},
                    containers=(Container.make(
                        name="c", requests={"cpu": 100, "memory": GI}),))
                for j in range(count)]

    mesh = S.make_mesh(devices)
    n_dev = int(mesh.devices.size)

    def cell(mesh_arg):
        ts = TPUScheduler(percentage_of_nodes_to_score=100, mesh=mesh_arg)
        # warmup: compile the (bucket, class) signature outside the window
        warm = ts.schedule_burst(mk_pods("warm", 8), infos, names,
                                 bucket=n_pods)
        assert warm is not None, "shard cell refused the warmup burst"
        t0 = _t.perf_counter()
        hosts = ts.schedule_burst(mk_pods("p", n_pods), infos, names,
                                  bucket=n_pods)
        dt = _t.perf_counter() - t0
        assert hosts is not None, "shard cell refused the measured burst"
        return ts, hosts, dt

    ts, hosts, dt = cell(mesh)
    if verify:
        _ts1, hosts1, _dt1 = cell(None)
        assert hosts == hosts1, (
            "sharded cell diverged from single-device at "
            f"{n_nodes} nodes: first diff at "
            f"{next(i for i, (a, b) in enumerate(zip(hosts, hosts1)) if a != b)}")
    n_pad = ts.encoder._batch.n_pad
    return {
        "nodes": n_nodes,
        "pods": n_pods,
        "pods_bound": sum(1 for h in hosts if h is not None),
        "pods_per_s": round(n_pods / dt, 1) if dt else 0.0,
        "devices": n_dev,
        "per_device_node_rows": n_pad // max(n_dev, 1),
        "verified_vs_single_device": bool(verify),
    }


def run_serve_cell(n_nodes: int = 1000, arrival_rate: float = 2000.0,
                   duration: float = 30.0, window: int = 2048,
                   depth: int = 3, max_depth: Optional[int] = None,
                   mesh=None, parity_windows: int = 3,
                   parity_pods: int = 256, seed: int = 0,
                   max_resident: Optional[int] = None) -> dict:
    """Arrival-driven serving cell (`bench.py --mode serve`): an
    ArrivalGenerator feeds pods at `arrival_rate`/s for `duration`
    seconds while a ServeLoop (window_size=`window`, launch-queue depth
    `depth`) cuts fused windows from the live activeQ, with a
    BackpressureGate shedding creates past `max_depth` (default: two
    seconds of arrivals) with 429 + Retry-After.

    Scores SUSTAINED pods/s over the arrival window (not a drain of a
    pre-built backlog) AND the ledger-derived startup percentiles
    (admission->commit — the accepted create IS the left boundary, so
    queue wait and shed-then-readmit backoffs are scored honestly)
    against the density.go 5 s SLO. Two in-cell audits gate the numbers:

    - all-admitted-or-429'd: every generated arrival either landed in
      the store AND got bound, or was shed and is accounted (re-admitted
      later, or given up after the client's retry budget) — nothing is
      silently dropped by gate, queue, or loop;
    - parity: after the timed window, `parity_windows` serve windows of
      fresh arrivals run with the flight recorder in replay mode and
      every captured launch is re-derived through the serial oracle —
      `parity_violations` must be 0 (decisions under arrival load are
      the same bits a serial oracle produces).

    Serving means pods COMPLETE: a drain bench's resident set only
    grows, but minutes at thousands of arrivals/s would exceed any
    fixed cluster's capacity. A completion reaper (the hollow stand-in
    for workloads finishing) deletes the oldest BOUND arrivals whenever
    the resident set exceeds `max_resident` (default: half the cell's
    pod capacity), so the cell reaches a steady state — arrivals in,
    completions out — and the SLO is scored in the regime the issue
    names. Reaped pods stay in the audit: created == still-in-store +
    reaped, and nothing admitted is ever lost.

    The single-threaded cooperative drive (gen.tick interleaved with
    loop.step) keeps the arrival sequence deterministic per seed; wall
    pacing still holds because tick() creates whatever the elapsed time
    owes."""
    import time as _t
    from collections import deque
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.obs import flight as obs_flight
    from kubernetes_tpu.obs.ledger import LEDGER
    from kubernetes_tpu.serve import ArrivalGenerator, ServeLoop
    from kubernetes_tpu.store.store import (MODIFIED, NODES, ExpiredError,
                                            NotFoundError)
    GI = 1024 ** 3
    est = int(arrival_rate * duration)
    # 64k-event watch window: the serve consumers (informers + the reap
    # watch) are pumped every step, so their backlog stays tiny — the old
    # 256k ring only meant the event log GREW for the first ~45 s of a
    # soak, and every gen2 GC pass over that still-growing heap landed as
    # a multi-ms pause inside some window's prologue (round-17 tail fix)
    store = Store(watch_log_size=1 << 16)
    for i in range(n_nodes):
        # uneven zones (n % 3 != 0 at most sizes) keep NodeTree rotation
        # live — serving must replay the same walk the oracle does
        store.create(NODES, Node(
            name=f"node-{i}",
            labels={"failure-domain.beta.kubernetes.io/zone":
                    f"zone-{i % 3}",
                    "kubernetes.io/hostname": f"node-{i}"},
            allocatable={"cpu": 4000, "memory": 32 * GI, "pods": 110}))
    sched = Scheduler(store, use_tpu=True,
                      percentage_of_nodes_to_score=100, mesh=mesh)
    sched.sync()
    loop = ServeLoop(sched, window_size=window, depth=depth)
    # warmup BEFORE the gate attaches: jit compiles ride ungated creates
    warm = ArrivalGenerator(store, rate=10 ** 9, total=64,
                            name_prefix="warm-", seed=seed)
    warm.tick()
    warm.tick()
    loop.drain(timeout=30.0)
    gate = loop.attach_gate(
        max_depth=(int(max_depth) if max_depth is not None
                   else max(4 * window, int(2 * arrival_rate))),
        # a calmer Retry-After floor for over-capacity cells: the base
        # 50 ms suggestion let shed clients re-arrive six-figure times
        # per second, and the retry storm itself ate serving capacity
        # (no effect on cells that keep up — they never shed)
        retry_after_base=0.25)
    LEDGER.reset()
    gen = ArrivalGenerator(store, rate=arrival_rate, seed=seed)
    # completion reaper: a watch collects binds in commit order; when the
    # resident arrival set outgrows `max_resident` the oldest bound pods
    # are deleted (the hollow "workload finished"), keeping the cell in
    # the steady serving regime instead of filling the cluster
    cap = n_nodes * min(110, 4000 // 100)   # the cell's pod capacity
    resident_target = (int(max_resident) if max_resident is not None
                       else max(4 * window, cap // 2))
    reap_watch = store.watch(PODS)
    bound_fifo: deque = deque()
    seen_bound: set = set()
    reaped = 0

    def reap() -> None:
        nonlocal reaped
        try:
            events = reap_watch.drain()
        except ExpiredError:       # dropped-with-resync: rebuild from list
            events = []
            bound_fifo.clear()
            seen_bound.clear()
            for p in store.list(PODS)[0]:
                if p.node_name and p.name.startswith(gen.name_prefix):
                    bound_fifo.append(p.key)
                    seen_bound.add(p.key)
        for ev in events:
            if ev.type == MODIFIED and ev.obj.node_name \
                    and ev.obj.name.startswith(gen.name_prefix) \
                    and ev.obj.key not in seen_bound:
                bound_fifo.append(ev.obj.key)
                seen_bound.add(ev.obj.key)
        if len(bound_fifo) > resident_target:
            batch = []
            while len(bound_fifo) > resident_target:
                batch.append(bound_fifo.popleft())
            # ONE batched delete per reap pass (one store lock + one
            # fan-out flush) — per-pod deletes put one lock+flush per
            # completion on the serving loop's critical path
            reaped += len(store.delete_many(PODS, batch))

    # GC posture of a serving process: full collection BEFORE the timed
    # window, then freeze the steady heap and re-freeze periodically —
    # without this, cyclic-GC gen2 passes over the growing heap (measured
    # ~127 ms each, 16 per 25 s cell) land as stop-the-world pauses
    # inside window prologues, and the backlog each pause leaves behind
    # compounds into oversized windows (round-17 tail fix; the pauses
    # showed up as the encode phase's p99)
    import gc as _gc
    _gc.collect()
    _gc.freeze()
    _gc_thresholds = _gc.get_threshold()
    # young generations keep collecting (most garbage dies there); the
    # full-heap generation is deferred to the explicit collect after the
    # run — a serving process cannot afford 100ms+ stop-the-world passes
    # on its window critical path
    _gc.set_threshold(_gc_thresholds[0], _gc_thresholds[1], 1 << 16)
    bound0 = loop.pods_bound
    t0 = _t.perf_counter()
    t_end = t0 + duration
    while _t.perf_counter() < t_end:
        # reap BEFORE the arrivals tick: the fresh creates then land
        # immediately adjacent to the step's informer pump, so the
        # admission (watch-to-enqueue) phase measures delivery, not the
        # reaper's housekeeping
        reap()
        gen.tick()
        if loop.step() == 0:
            _t.sleep(min(loop.tick_interval, 0.001))
    elapsed = _t.perf_counter() - t0
    sustained = (loop.pods_bound - bound0) / elapsed if elapsed else 0.0
    # arrivals stop; settle every shed retry and drain the queue (keep
    # reaping: a full cluster must keep completing for the tail to land)
    deadline = _t.perf_counter() + 90.0
    while _t.perf_counter() < deadline:
        gen.flush_retries(timeout=0.5)
        reap()
        if loop.step() == 0 and gen.stats()["pending_retry"] == 0 \
                and sched.queue.num_pending() == 0:
            break
    reap_watch.stop()
    # normal GC posture for the audits and beyond; the deferred full
    # collection runs here, OFF the timed window
    _gc.set_threshold(*_gc_thresholds)
    _gc.unfreeze()
    _gc.collect()
    g = gen.stats()
    # -- audit 1: all-admitted-or-429'd ----------------------------------
    measured = [p for p in store.list(PODS)[0]
                if p.name.startswith(gen.name_prefix)]
    unbound = sum(1 for p in measured if not p.node_name)
    assert len(measured) + reaped == g["created"], \
        (f"arrival accounting leak: {len(measured)} in store + {reaped} "
         f"reaped != {g['created']} created")
    assert unbound == 0, f"{unbound} admitted arrivals never bound"
    assert g["attempted"] == g["created"] + g["gave_up"] \
        + g["pending_retry"], f"arrival accounting leak: {g}"
    led = LEDGER.snapshot()
    # -- audit 2: serve-window parity through the flight recorder --------
    obs_flight.RECORDER.configure(mode="replay",
                                  capacity=max(parity_windows, 1))
    obs_flight.RECORDER.clear()
    par = ArrivalGenerator(store, rate=10 ** 9, total=parity_pods,
                           name_prefix="par-", seed=seed + 1)
    violations: list = []
    try:
        while not par.finished():
            par.tick()
            loop.step()
        loop.drain(timeout=30.0)
        violations = obs_flight.RECORDER.replay_all()
    finally:
        obs_flight.RECORDER.configure(mode="digest")
        obs_flight.RECORDER.clear()
    return {
        "nodes": n_nodes,
        "arrival_rate": arrival_rate,
        "duration": round(elapsed, 2),
        "sustained_pods_per_s": round(sustained, 1),
        "window": window,
        "depth": depth,
        "windows_cut": loop.windows_cut,
        "idle_ticks": loop.idle_ticks,
        "startup_p50": led["startup_p50"],
        "startup_p99": led["startup_p99"],
        "startup_slo_ok": led["startup_slo_ok"],
        # windowed twins (trailing 30 s): a late-run stall flips these
        # while the cumulative numbers above still average it away
        "startup_p50_windowed": led["startup_p50_windowed"],
        "startup_p99_windowed": led["startup_p99_windowed"],
        "startup_slo_ok_windowed": led["startup_slo_ok_windowed"],
        "slo_burn_rate": led["slo_burn_rate"],
        "phase_split": led["phase_split"],
        # the round-17 host-prologue score: encode + admission
        # pod-seconds (the two phases the encode-at-admission row cache
        # and the batched ingest attack), absolute and per scheduled pod
        # — test_bench_floors floors the per-pod number against the
        # round-16 recorded baseline
        "prologue_phase_split": {
            "encode_pod_seconds": led["phase_split"]["encode"],
            "admission_pod_seconds": led["phase_split"]["admission"],
            "per_scheduled_pod": round(
                (led["phase_split"]["encode"]
                 + led["phase_split"]["admission"])
                / max(1, led["pods_completed"]), 6),
        },
        "pods_completed": led["pods_completed"],
        "workload_reaped": reaped,
        "resident_target": resident_target,
        "arrivals": g,
        "admission": gate.debug_state(),
        "audit_all_admitted_or_429": True,   # the asserts above gate it
        "parity_violations": len(violations),
        "parity_errors": violations[:3],
    }


def run_fleet_cell(n_nodes: int = 1000, instances: int = 2,
                   arrival_rate: float = 4000.0, duration: float = 20.0,
                   window: int = 2048, depth: int = 3,
                   n_shards: Optional[int] = None,
                   use_tpu: bool = True, seed: int = 0,
                   max_resident: Optional[int] = None) -> dict:
    """Active-active fleet cell (`bench.py --mode fleet`, round 18):
    `instances` FleetInstances — each a full scheduler with its own
    informers, activeQ, and launch queue — run on their OWN THREADS
    against ONE shared store, partitioned by namespace-hash Lease claims
    with fenced writes, while an ArrivalGenerator feeds namespace-spread
    pods at `arrival_rate`/s for `duration` seconds through one
    fleet-wide backpressure gate. Scores AGGREGATE sustained pods/s.

    Three in-cell audits gate the number:
    - zero-double-bind: a BindAuditor folds the shared pod watch for the
      whole run; any nodeName transition non-empty -> different
      non-empty fails the cell (the fleet_double_binds_total tripwire);
    - all-admitted-or-429'd: every generated arrival either landed AND
      bound, or was shed and accounted — same contract as the serve cell;
    - partition sanity: live claim sets stay disjoint at every probe.

    A completion reaper (serve-cell pattern) keeps the resident set in
    steady state so minutes-scale fleet soaks don't fill the cluster."""
    import threading as _th
    import time as _t
    from collections import deque
    from kubernetes_tpu.api.types import Node, Pod, Container
    from kubernetes_tpu.fleet import FleetInstance, BindAuditor, shard_of
    from kubernetes_tpu.obs.ledger import LEDGER
    from kubernetes_tpu.serve import ArrivalGenerator
    from kubernetes_tpu.serve.backpressure import fleet_gate
    from kubernetes_tpu.store.store import MODIFIED, NODES, ExpiredError
    GI = 1024 ** 3
    MI = 1024 ** 2
    n_shards = int(n_shards) if n_shards else max(8, 4 * instances)
    store = Store(watch_log_size=1 << 17)
    for i in range(n_nodes):
        store.create(NODES, Node(
            name=f"node-{i}",
            labels={"failure-domain.beta.kubernetes.io/zone":
                    f"zone-{i % 3}",
                    "kubernetes.io/hostname": f"node-{i}"},
            allocatable={"cpu": 4000, "memory": 32 * GI, "pods": 110}))
    idents = [f"sched-{i}" for i in range(int(instances))]
    fleet = [FleetInstance(store, ident, idents, use_tpu=use_tpu,
                           window=window, depth=depth, n_shards=n_shards,
                           lease_duration=5.0, renew_deadline=3.0,
                           percentage_of_nodes_to_score=100)
             for ident in idents]
    for inst in fleet:
        inst.sync()
    # claims settle + jit warmup BEFORE the gate attaches and the clock
    # starts: feed a handful of ungated pods and drain them
    n_prefix = "fl-"
    import zlib as _zlib

    def mkpod(name: str) -> Pod:
        # namespace spread drives the shard partition (crc32 of the
        # namespace): 4*shards namespaces cover every shard
        ns = f"ns-{_zlib.crc32(name.encode()) % (4 * n_shards)}"
        return Pod(name=name, namespace=ns, labels={"app": "fleet"},
                   containers=(Container.make(
                       name="c", requests={"cpu": 100,
                                           "memory": 500 * MI}),))

    warm = ArrivalGenerator(store, rate=10 ** 9, total=32 * instances,
                            pod_fn=mkpod, name_prefix="flwarm-", seed=seed)
    for _ in range(3):
        warm.tick()
        for inst in fleet:
            inst.step()
    def fleet_idle() -> bool:
        """Nothing pending anywhere: queues empty AND every instance's
        pod-informer backlog drained — the queue alone lags creates by
        one pump, so checking it in isolation races the last arrivals
        into a stopped thread's undelivered backlog."""
        for inst in fleet:
            if inst.sched.queue.num_pending() > 0:
                return False
            if inst.sched.informers.informer(PODS).backlog() > 0:
                return False
        return True

    deadline_warm = _t.perf_counter() + 60.0
    while _t.perf_counter() < deadline_warm:
        if sum(inst.step() for inst in fleet) == 0 and fleet_idle():
            break
    auditor = BindAuditor(store)
    gate = fleet_gate([inst.loop for inst in fleet],
                      max_depth=max(4 * window, int(2 * arrival_rate)))
    store.admission_gate = gate
    LEDGER.reset()
    gen = ArrivalGenerator(store, rate=arrival_rate, pod_fn=mkpod,
                           name_prefix=n_prefix, seed=seed)
    # completion reaper (serve-cell pattern): oldest bound arrivals are
    # deleted past the resident target so the cell reaches steady state
    cap = n_nodes * min(110, 4000 // 100)
    resident_target = (int(max_resident) if max_resident is not None
                       else max(4 * window, cap // 2))
    reap_watch = store.watch(PODS)
    bound_fifo: deque = deque()
    seen_bound: set = set()
    reaped = 0

    def reap() -> None:
        nonlocal reaped
        try:
            events = reap_watch.drain()
        except ExpiredError:
            events = []
            bound_fifo.clear()
            seen_bound.clear()
            for p in store.list(PODS)[0]:
                if p.node_name and p.name.startswith(n_prefix):
                    bound_fifo.append(p.key)
                    seen_bound.add(p.key)
        for ev in events:
            if ev.type == MODIFIED and ev.obj.node_name \
                    and ev.obj.name.startswith(n_prefix) \
                    and ev.obj.key not in seen_bound:
                bound_fifo.append(ev.obj.key)
                seen_bound.add(ev.obj.key)
        if len(bound_fifo) > resident_target:
            batch = []
            while len(bound_fifo) > resident_target:
                batch.append(bound_fifo.popleft())
            reaped += len(store.delete_many(PODS, batch))

    stop = _th.Event()

    def drive(inst: FleetInstance) -> None:
        while not stop.is_set():
            if inst.step() == 0:
                _t.sleep(0.001)

    threads = [_th.Thread(target=drive, args=(inst,), daemon=True,
                          name=f"fleet-{inst.identity}")
               for inst in fleet]
    bound0 = sum(inst.loop.pods_bound for inst in fleet)
    partition_overlap = False
    t0 = _t.perf_counter()
    for th in threads:
        th.start()
    t_end = t0 + duration
    while _t.perf_counter() < t_end:
        reap()
        gen.tick()
        auditor.scan()
        # partition sanity probe: live claim sets stay disjoint
        seen: set = set()
        for inst in fleet:
            owned = inst.claims.owned()
            if owned & seen:
                partition_overlap = True
            seen |= owned
        _t.sleep(0.002)
    elapsed = _t.perf_counter() - t0
    aggregate = (sum(inst.loop.pods_bound for inst in fleet) - bound0) \
        / elapsed if elapsed else 0.0
    # settle: arrivals stop; shed retries, informer backlogs, and the
    # queues drain. The idle condition must hold over CONSECUTIVE polls:
    # the drive threads are still stepping, and a single snapshot can
    # catch a window mid-flight (popped pods make a queue read empty)
    settle_deadline = _t.perf_counter() + 90.0
    idle_polls = 0
    while _t.perf_counter() < settle_deadline:
        gen.flush_retries(timeout=0.2)
        reap()
        auditor.scan()
        if gen.stats()["pending_retry"] == 0 and fleet_idle():
            idle_polls += 1
            if idle_polls >= 3:
                break
        else:
            idle_polls = 0
        _t.sleep(0.05)
    stop.set()
    for th in threads:
        th.join(timeout=5.0)
    # post-stop cooperative drain: a step that completed right at the
    # stop boundary may have re-queued a pod (failed decision) or left
    # undelivered informer events — finish them sequentially, bounded
    drain_deadline = _t.perf_counter() + 30.0
    while not fleet_idle() and _t.perf_counter() < drain_deadline:
        reap()
        for inst in fleet:
            inst.step()
    auditor.scan()
    reap_watch.stop()
    auditor.stop()
    g = gen.stats()
    measured = [p for p in store.list(PODS)[0]
                if p.name.startswith(n_prefix)]
    unbound = sum(1 for p in measured if not p.node_name)
    assert len(measured) + reaped == g["created"], \
        (f"fleet accounting leak: {len(measured)} in store + {reaped} "
         f"reaped != {g['created']} created")
    assert unbound == 0, f"{unbound} admitted arrivals never bound"
    assert not auditor.violations, \
        f"DOUBLE BINDS observed: {auditor.violations[:5]}"
    assert not partition_overlap, "live shard claims overlapped"
    led = LEDGER.snapshot()
    from kubernetes_tpu.fleet import BIND_CONFLICTS
    return {
        "nodes": n_nodes,
        "instances": int(instances),
        "shards": n_shards,
        "arrival_rate": arrival_rate,
        "duration": round(elapsed, 2),
        "aggregate_pods_per_s": round(aggregate, 1),
        "per_instance_pods_bound": {
            inst.identity: inst.loop.pods_bound for inst in fleet},
        "fenced_waves": sum(inst.sched.fenced_waves for inst in fleet),
        "bind_conflicts_requeued":
            BIND_CONFLICTS.labels("requeued").value,
        "bind_conflicts_fenced": BIND_CONFLICTS.labels("fenced").value,
        "double_binds": len(auditor.violations),
        "partition_disjoint": not partition_overlap,
        "startup_p50": led["startup_p50"],
        "startup_p99": led["startup_p99"],
        "startup_slo_ok": led["startup_slo_ok"],
        "startup_p50_windowed": led["startup_p50_windowed"],
        "startup_p99_windowed": led["startup_p99_windowed"],
        "startup_slo_ok_windowed": led["startup_slo_ok_windowed"],
        "slo_burn_rate": led["slo_burn_rate"],
        "workload_reaped": reaped,
        "arrivals": g,
        "admission": gate.debug_state(),
        "audit_all_admitted_or_429": True,   # the asserts above gate it
        "audit_no_double_bind": True,
    }


#: the shadow profile of the tuner cell (round 22): starts with the
#: DefaultProvider vector; the tuner writes the candidate row into it
TUNE_SHADOW_PROFILE = "shadow-tuner"


def run_tuner_cell(n_nodes: int = 256, arrival_rate: float = 250.0,
                   duration: float = 12.0, window: int = 512,
                   depth: int = 2, use_tpu: bool = True, seed: int = 0,
                   search_budget: int = 48,
                   record_worlds: int = 4,
                   install_at_frac: float = 0.3) -> dict:
    """Closed-loop learned-scoring cell (`bench.py --mode tune`, round
    22) — the full tuner loop in one run, three phases:

    A. RECORD: a solo scheduler (replay-mode flight recorder) schedules
       a mixed-size workload; the recorded bursts become the offline
       simulator's worlds.
    B. SEARCH: a seeded CEM (`tuner.tune`) over integer weight rows
       scores candidates against the worlds; the same search re-run with
       the same seed must reproduce the winner bit-for-bit (the
       determinism audit, asserted in-cell).
    C. SHADOW SERVE: two FleetInstances over one store — the incumbent
       profile on one, the shadow profile on the other (round-18
       partitioning by claimed profile = the A/B lane). Two arrival
       streams (tn-i-* / tn-s-*) feed the lanes at arrival_rate/2 each;
       MID-RUN the tuner installs the searched row into the shadow via
       ProfileSet.set_row + reload_profiles (a live tensor-row write).
       The replay-mode recorder runs the whole phase, so the final
       parity pass proves records straddling the write still replay
       bit-identically (the capture pins a ProfileSet snapshot). A
       ShadowTuner observe tick + timeseries scrape each ~250 ms builds
       the evidence the PromotionGate judges at the end.

    In-cell audits: zero double-binds (BindAuditor), all arrivals bound,
    zero flight-replay mismatches while rows churned, deterministic
    search. The objective readout (windowed per-lane p99 + packing
    utilization, shadow-vs-incumbent bound ratio) is returned for the
    bench floor: the tuned lane must win on utilization and/or p99 at
    >= 0.9x the incumbent lane's throughput."""
    import random as _random
    import time as _t
    import zlib as _zlib
    from kubernetes_tpu.api.types import Container, Node, Pod
    from kubernetes_tpu.factory import DEFAULT_PRIORITY_WEIGHTS
    from kubernetes_tpu.fleet import BindAuditor, FleetInstance
    from kubernetes_tpu.obs.flight import RECORDER
    from kubernetes_tpu.obs.ledger import LEDGER
    from kubernetes_tpu.obs.timeseries import SCRAPER, SeriesView
    from kubernetes_tpu.profiles import (
        DEFAULT_PROFILE_NAME, ProfileSet, SchedulingProfile)
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.serve import ArrivalGenerator
    from kubernetes_tpu.store.store import NODES
    from kubernetes_tpu.tuner import (
        PromotionGate, ShadowTuner, simulate, tune, worlds_from_recorder)
    from kubernetes_tpu.tuner.controller import (
        lane_utilization, prefix_lanes)
    GI = 1024 ** 3
    MI = 1024 ** 2
    cpu_sizes = (100, 150, 250)     # mixed sizes give packing traction

    def mknode(i: int) -> Node:
        return Node(
            name=f"node-{i}",
            labels={"failure-domain.beta.kubernetes.io/zone":
                    f"zone-{i % 3}",
                    "kubernetes.io/hostname": f"node-{i}"},
            allocatable={"cpu": 4000, "memory": 32 * GI, "pods": 110})

    # ---- phase A: record worlds --------------------------------------------
    RECORDER.configure(mode="replay", capacity=max(8, record_worlds))
    RECORDER.clear()
    store_a = Store()
    for i in range(max(16, n_nodes // 8)):
        store_a.create(NODES, mknode(i))
    sched_a = Scheduler(store_a, use_tpu=use_tpu,
                        percentage_of_nodes_to_score=100)
    sched_a.sync()
    rng = _random.Random(seed)
    for j in range(16 * record_worlds):
        store_a.create(PODS, Pod(
            name=f"w{j}", labels={"app": "tune"},
            containers=(Container.make(
                name="c", requests={"cpu": rng.choice(cpu_sizes),
                                    "memory": rng.choice(
                                        (1, 2, 4)) * GI}),)))
    sched_a.pump()
    while sched_a.schedule_burst(max_pods=16):
        pass
    sched_a.pump()
    worlds = worlds_from_recorder(limit=record_worlds)
    assert worlds, "phase A recorded no replayable worlds"

    # ---- phase B: seeded search + determinism audit ------------------------
    keys = ["LeastRequestedPriority", "MostRequestedPriority",
            "BalancedResourceAllocation", "SelectorSpreadPriority"]
    t_search0 = _t.perf_counter()
    result = tune(worlds, keys, seed=seed,
                  incumbent=DEFAULT_PRIORITY_WEIGHTS,
                  budget=search_budget)
    search_s = _t.perf_counter() - t_search0
    twin = tune(worlds, keys, seed=seed,
                incumbent=DEFAULT_PRIORITY_WEIGHTS, budget=search_budget)
    assert (twin.best_weights, twin.best_reward) == \
        (result.best_weights, result.best_reward), \
        "search is nondeterministic under a fixed seed"
    incumbent_reward = sum(
        simulate(w, DEFAULT_PRIORITY_WEIGHTS).reward for w in worlds)

    # ---- phase C: shadow serve + mid-run row write + gate ------------------
    RECORDER.configure(mode="replay", capacity=16)
    RECORDER.clear()
    store = Store(watch_log_size=1 << 16)
    for i in range(n_nodes):
        store.create(NODES, mknode(i))
    pset = ProfileSet([
        SchedulingProfile(DEFAULT_PROFILE_NAME),
        SchedulingProfile(TUNE_SHADOW_PROFILE),   # starts = default row
    ])
    lanes = ((DEFAULT_PROFILE_NAME, "tn-i-"),
             (TUNE_SHADOW_PROFILE, "tn-s-"))
    idents = ["tune-inc", "tune-shd"]
    fleet = [FleetInstance(store, idents[k], [idents[k]],
                           profile=lanes[k][0], profiles=pset,
                           use_tpu=use_tpu, window=window, depth=depth,
                           n_shards=8, lease_duration=5.0,
                           renew_deadline=3.0,
                           percentage_of_nodes_to_score=100)
             for k in range(2)]
    for inst in fleet:
        inst.sync()

    def mkpod_for(profile: str):
        def mk(name: str) -> Pod:
            h = _zlib.crc32(name.encode())
            return Pod(name=name, namespace=f"ns-{h % 32}",
                       labels={"app": "tune"}, scheduler_name=profile,
                       containers=(Container.make(
                           name="c",
                           requests={"cpu": cpu_sizes[h % len(cpu_sizes)],
                                     "memory": 500 * MI}),))
        return mk

    def fleet_idle() -> bool:
        for inst in fleet:
            if inst.sched.queue.num_pending() > 0:
                return False
            if inst.sched.informers.informer(PODS).backlog() > 0:
                return False
        return True

    # warmup (jit + claim settling for both profiles), outside the clock
    for prof, prefix in lanes:
        warm = ArrivalGenerator(store, rate=10 ** 9, total=16,
                                pod_fn=mkpod_for(prof),
                                name_prefix=f"{prefix}warm-", seed=seed)
        for _ in range(3):
            warm.tick()
            for inst in fleet:
                inst.step()
    deadline_warm = _t.perf_counter() + 60.0
    while _t.perf_counter() < deadline_warm:
        if sum(inst.step() for inst in fleet) == 0 and fleet_idle():
            break

    auditor = BindAuditor(store)
    LEDGER.reset()
    SCRAPER.reset()
    lane_match = prefix_lanes("tn-i-", "tn-s-")
    tuner = ShadowTuner(pset, TUNE_SHADOW_PROFILE,
                        incumbent=DEFAULT_PROFILE_NAME,
                        schedulers=fleet, lane_match=lane_match,
                        window=max(duration, 10.0))
    gens = [ArrivalGenerator(store, rate=arrival_rate / 2,
                             pod_fn=mkpod_for(prof), name_prefix=prefix,
                             seed=seed + k)
            for k, (prof, prefix) in enumerate(lanes)]
    installed_at = None
    last_obs = 0.0
    bound0 = [inst.loop.pods_bound for inst in fleet]
    t0 = _t.perf_counter()
    t_end = t0 + duration
    # single-threaded round-robin drive: the mid-run set_row +
    # reload_profiles lands BETWEEN steps, never inside a burst
    while _t.perf_counter() < t_end:
        for g in gens:
            g.tick()
        for inst in fleet:
            inst.step()
        auditor.scan()
        now = _t.perf_counter()
        if installed_at is None and now - t0 >= install_at_frac * duration:
            tuner.install(result.best_weights)      # the live row write
            installed_at = now - t0
        if now - last_obs >= 0.25:
            tuner.observe(fleet[0].sched._snapshot.node_infos)
            SCRAPER.sample()
            last_obs = now
    elapsed = _t.perf_counter() - t0
    if installed_at is None:          # degenerate short durations
        tuner.install(result.best_weights)
        installed_at = elapsed
    # settle: drain both lanes, then one last observe/scrape
    settle_deadline = _t.perf_counter() + 60.0
    while _t.perf_counter() < settle_deadline:
        for g in gens:
            g.flush_retries(timeout=0.1)
        if sum(inst.step() for inst in fleet) == 0 and fleet_idle() \
                and all(g.stats()["pending_retry"] == 0 for g in gens):
            break
    auditor.scan()
    tuner.observe(fleet[0].sched._snapshot.node_infos)
    SCRAPER.sample()
    auditor.stop()

    # parity while rows churn: every recorded burst (both lanes, before
    # AND after the set_row write) must replay bit-identically — the
    # flight capture pinned a ProfileSet snapshot per burst
    parity_errs = RECORDER.replay_all()
    assert parity_errs == [], \
        f"flight replay mismatches across the row write: {parity_errs[:5]}"
    RECORDER.configure(mode="digest")
    RECORDER.clear()

    measured = [p for p in store.list(PODS)[0]
                if p.name.startswith("tn-")]
    unbound = [p.key for p in measured if not p.node_name]
    assert not unbound, f"{len(unbound)} arrivals never bound"
    assert not auditor.violations, \
        f"DOUBLE BINDS observed: {auditor.violations[:5]}"

    # objective readout + the gate's verdict
    snapshot_infos = fleet[0].sched._snapshot.node_infos
    now = _t.perf_counter()
    lane_stats = {}
    for lane, match in lane_match.items():
        lane_stats[lane] = {
            "p99": LEDGER.window_percentile(
                0.99, window=elapsed + 60.0, now=now, match=match),
            "utilization": lane_utilization(snapshot_infos, match),
            "committed": LEDGER.window_count(
                window=elapsed + 60.0, now=now, match=match),
        }
    bound_by = {idents[k]: fleet[k].loop.pods_bound - bound0[k]
                for k in range(2)}
    inc_bound = bound_by["tune-inc"]
    shd_bound = bound_by["tune-shd"]
    gate = PromotionGate()
    decision = tuner.apply(gate.decide(SeriesView(SCRAPER.series())))
    sh, inc = lane_stats["shadow"], lane_stats["incumbent"]
    util_win = sh["utilization"] > inc["utilization"]
    p99_win = sh["p99"] < inc["p99"]
    led = LEDGER.snapshot()
    return {
        "nodes": n_nodes,
        "arrival_rate": arrival_rate,
        "duration": round(elapsed, 2),
        "worlds_recorded": len(worlds),
        "search": result.as_dict(),
        "search_seconds": round(search_s, 3),
        "search_deterministic": True,      # asserted above
        "incumbent_sim_reward": round(incumbent_reward, 3),
        "tuned_vs_incumbent_reward": round(
            result.best_reward / incumbent_reward, 4)
        if incumbent_reward else None,
        "installed_at_s": round(installed_at, 2),
        "profile_version": pset.version,
        "lanes": {l: {"p99": round(s["p99"], 4),
                      "utilization": (None if s["utilization"] !=
                                      s["utilization"] else
                                      round(s["utilization"], 4)),
                      "committed": s["committed"]}
                  for l, s in lane_stats.items()},
        "shadow_bound": shd_bound,
        "incumbent_bound": inc_bound,
        "shadow_vs_incumbent_throughput": round(
            shd_bound / inc_bound, 4) if inc_bound else None,
        "objective_win_utilization": util_win,
        "objective_win_p99": p99_win,
        "objective_win": bool(util_win or p99_win),
        "gate_decision": decision["decision"],
        "gate_reason": decision["reason"],
        "gate_stats": decision["stats"],
        "parity_violations": 0,            # asserted above
        "double_binds": len(auditor.violations),
        "audit_no_double_bind": True,
        "startup_p99": led["startup_p99"],
        "startup_p99_windowed": led["startup_p99_windowed"],
        "pods_completed": led["pods_completed"],
    }


# the benchmark matrices (scheduler_bench_test.go:40-118)
BENCHMARK_MATRIX = {
    "plain": [(100, 0), (100, 1000), (1000, 0), (1000, 1000), (5000, 1000)],
    "anti-affinity": [(500, 250), (500, 5000), (1000, 1000), (5000, 1000)],
    "affinity": [(500, 250), (500, 5000), (1000, 1000), (5000, 1000)],
    "node-affinity": [(500, 250), (500, 5000), (1000, 1000), (5000, 1000)],
    # gang (PodGroup) cells: (nodes, gang_size) — run via run_gang_cell
    "gang": [(1000, 8), (1000, 64), (5000, 512)],
    # preemption pressure cells: (nodes, victims, preemptors-per-wave) —
    # run via run_preempt_cell (warm victim table, one launch per wave;
    # 128 = one full PRESSURE_B_CAP chunk, the throughput configuration)
    "preempt": [(1000, 10000, 16), (1000, 10000, 128)],
    # commit-core cells: (pods-per-wave, waves, watchers) — run via
    # run_commit_cell (the round-11 store-write + fan-out tail; the
    # 4096-pod cell is one full default scheduler wave). The round-20
    # watcher-scaling cells shrink the wave so the cell measures fan-out,
    # not writes: 1k/10k watchers sharing one subscription class, and the
    # 100k-watcher north-star cell as the slow tier-2 gate.
    "commit": [(1024, 8, 8), (4096, 8, 8),
               (256, 4, 1000), (256, 4, 10000),
               (64, 2, 100_000)],   # 100k cell: slow tier-2
    # mesh-sharded scale cells: (nodes, pods) — run via run_shard_cell
    # over every visible device. These node counts cannot fit one chip's
    # HBM once the resident planes + victim table are counted (PROFILE.md
    # round-15); the 50k cell is the slow-marked tier-2 gate
    "shard": [(50_000, 2000), (100_000, 2000), (200_000, 1000)],
    # arrival-driven serving cells: (nodes, arrivals/s, seconds) — run
    # via run_serve_cell. The 1000n/2000rps/30s cell is the acceptance
    # gate (startup_p99 <= 5s, zero parity violations, every arrival
    # admitted-or-429'd); the 4000rps cell is the round-17 raised
    # sustained-rate gate (the batched prologue must keep up on CPU);
    # the 5000rps cell probes the shed regime.
    "serve": [(1000, 2000, 30), (1000, 4000, 30), (1000, 5000, 30),
              (5000, 2000, 30)],
    # active-active fleet cells: (nodes, instances, arrivals/s, seconds)
    # — run via run_fleet_cell. The 2-instance cell is the round-18
    # acceptance gate (aggregate >= the solo serve baseline with the
    # zero-double-bind audit); the 4-instance cell probes claim churn
    # at higher membership.
    "fleet": [(1000, 2, 4000, 20), (1000, 4, 4000, 20)],
    # soak scoreboard cells (round 21): (nodes, instances, arrivals/s,
    # seconds, watchers) — run via perf.soak.run_soak_cell (fleet x
    # mixed profiles x serve arrivals x churn x chaos with the
    # time-series scraper + verdict engine attached). The 10k-watcher
    # cell is the standing gate; the 100k-watcher/120s cell is the
    # million-object north star (ROADMAP item 1) and slow tier-2 —
    # ~240k pods through the store, ~480k bind/delete events fanned
    # through ~64 shared classes (PROFILE.md round 21 arithmetic).
    "soak": [(1000, 2, 1500, 45, 10_000),
             (2000, 2, 2000, 120, 100_000)],   # 100k cell: slow tier-2
    # closed-loop tuner cells (round 22): (nodes, arrivals/s, seconds)
    # — run via run_tuner_cell (record worlds -> seeded CEM search with
    # an in-cell determinism audit -> two-instance shadow A/B serve with
    # a MID-RUN ProfileSet.set_row write, flight-replay parity across
    # it, and the promotion gate's verdict). The small cell is the
    # acceptance gate (tuned lane wins on utilization and/or p99 at
    # >= 0.9x throughput, zero double-binds, zero parity violations);
    # the large cell probes the loop at fleet-serve scale.
    "tune": [(256, 250, 12), (1000, 800, 20)],
}


def run_gang_cell(nodes: int = 1000, gang_size: int = 64,
                  pods: int = 1000, existing: int = 0,
                  use_tpu: bool = True, burst: int = 1024) -> PerfResult:
    """Gang matrix cell: `pods // gang_size` PodGroups of spec-identical
    members scheduled all-or-nothing through the burst path; throughput
    counts member pods. Asserts the atomicity contract (no partially
    bound group) before reporting — a gang-path regression fails the cell
    rather than reporting corrupt numbers."""
    from kubernetes_tpu.coscheduling.types import LABEL_POD_GROUP, PodGroup
    from kubernetes_tpu.store.store import PODGROUPS
    cfg = PerfConfig(nodes=nodes, existing_pods=existing, pods=pods,
                     use_tpu=use_tpu, burst=burst)
    store, sched = setup(cfg)
    MI = 1024 ** 2
    from kubernetes_tpu.api.types import Pod, Container

    def create_gangs(tag: str, count: int, size: int) -> None:
        for g in range(count):
            name = f"{tag}-{size}-{g}"
            store.create(PODGROUPS, PodGroup(name=name, min_member=size))
            for r in range(size):
                store.create(PODS, Pod(
                    name=f"{name}-r{r}",
                    labels={LABEL_POD_GROUP: name, "app": "gang"},
                    containers=(Container.make(
                        name="c",
                        requests={"cpu": 100, "memory": 500 * MI}),)))

    create_gangs("warmup", 1, gang_size)   # compile outside the window
    sched.pump()
    _drain(sched, cfg)
    sched.pump()
    n_groups = max(1, pods // gang_size)
    create_gangs("measured", n_groups, gang_size)
    sched.pump()
    before = sched.metrics.schedule_attempts["scheduled"]
    t0 = time.perf_counter()
    _drain(sched, cfg)
    elapsed = time.perf_counter() - t0
    sched.pump()
    by_group: dict[str, list] = {}
    for p in store.list(PODS)[0]:
        g = p.labels.get(LABEL_POD_GROUP)
        if g:
            by_group.setdefault(g, []).append(bool(p.node_name))
    partial = [g for g, flags in by_group.items()
               if any(flags) and not all(flags)]
    assert not partial, f"partially bound gangs: {partial[:5]}"
    scheduled = sched.metrics.schedule_attempts["scheduled"] - before
    throughput = scheduled / elapsed if elapsed > 0 else 0.0
    return PerfResult(scheduled, elapsed, throughput, throughput,
                      dict(sched.metrics.schedule_attempts))


def run_commit_cell(n_pods: int = 4096, waves: int = 8,
                    n_watchers: int = 8, impl: Optional[str] = None,
                    audit: Optional[list] = None,
                    watch_classes: int = 1,
                    shared_classes: bool = True) -> dict:
    """Commit-core cell (`bench.py --mode commit`): the store-write +
    fan-out tail of a burst wave in isolation — `waves` waves of `n_pods`
    binds each, every wave ONE `commit_wave` call (batched bind + the
    Scheduled audit-record creates) and ONE `fanout_wave` call, with
    `n_watchers` live pod watchers copying events out on their own
    threads (the overlap the core's GIL-released poll buys).

    Round 20: the watchers split across `watch_classes` distinct
    (kind, selector) subscription classes (1 = everyone shares one
    materialize-once/encode-once class — the north-star shape); half of
    each class drains the Event lane, half the serialize-once byte ring
    (the apiserver's wire encoding), so the copy-out phase pays both
    representations once per class. `shared_classes=False` runs the
    degenerate class-per-watcher mode — the pre-round-20 per-watcher
    fan-out path, the scaling floor's extrapolation baseline.

    Reports writes/s (binds + event creates landed; the watchers are
    ATTACHED during the timed loop, so every fanout_wave pays its cursor
    publishes) and copy-out events/s + bytes/s (the drain phase, timed
    on its own — on a single-core box a concurrent consumer just
    timeshares the GIL with the commit loop and turns both numbers into
    scheduler noise; the threaded-overlap correctness is pinned by
    tests/test_commit_core.py instead). `impl` pins the core
    ("native"/"twin"); when `audit` is a list, every wave's (missing,
    rv-after) and the full first-watcher event stream are appended so the
    caller can referee native vs twin bit-for-bit. The serial per-pod
    reference only runs at <= 1024 watchers (each serial verb's flush
    walks every watcher — at 100k that measures the walk, not the verb)."""
    from kubernetes_tpu.api.types import Container, Pod
    from kubernetes_tpu.apiserver.server import wire_line
    from kubernetes_tpu.store.record import EventRecorder
    store = Store(watch_log_size=max(1 << 17, 8 * n_pods * waves),
                  commit_core=impl, shared_watch_classes=shared_classes)
    store.set_wire_encoder(wire_line)
    recorder = EventRecorder(store)
    MI = 1024 ** 2
    # one fresh pod set PER WAVE: the round-18 rv-CAS bind refuses
    # re-binding an already-bound pod (the fleet's double-bind guard), so
    # the steady-state commit path is exercised with distinct unbound
    # pods each wave — the per-binding work (clone, setattr, rv, log
    # append) is identical to the old rebind loop
    for wv in range(waves):
        for j in range(n_pods):
            store.create(PODS, Pod(
                name=f"p{wv}-{j}", labels={"app": "commit"},
                containers=(Container.make(
                    name="c", requests={"cpu": 100, "memory": 500 * MI}),)))
    pods_by_key = {p.key: p for p in store.list(PODS)[0]}
    wave_keys = [[f"default/p{wv}-{j}" for j in range(n_pods)]
                 for wv in range(waves)]
    n_classes = max(1, min(watch_classes, n_watchers))
    watches = [store.watch(PODS, selector=f"wc{i % n_classes}")
               for i in range(n_watchers)]
    writes = 0
    t0 = time.perf_counter()
    for wv in range(waves):
        keys = wave_keys[wv]
        bindings = [(k, f"n{wv}") for k in keys]
        recs = recorder.make_pod_records([
            (pods_by_key[k], "Normal", "Scheduled",
             f"Successfully assigned {k} to n{wv}") for k in keys])
        missing = store.commit_wave(bindings, recs)
        store.fanout_wave()
        writes += 2 * len(bindings) - len(missing)
        if audit is not None:
            audit.append((list(missing), store.resource_version()))
    elapsed = time.perf_counter() - t0
    # copy-out phase: drain every watcher (Event materialization — once
    # per class in shared mode — happens here, on the consumer side; the
    # cost fan-out moved OFF the commit thread above). Odd watchers drain
    # the serialize-once byte ring instead of the Event lane, so each
    # class pays one materialization AND one wire encoding per event and
    # every classmate after the first serves shared objects/bytes.
    stats_before = store.watch_plane_state()
    delivered = 0
    audit_stream: list = []
    t1 = time.perf_counter()
    for i, w in enumerate(watches):
        if i % 2 == 1:
            delivered += len(w.drain_bytes())
            continue
        evs = w.drain()
        delivered += len(evs)
        if audit is not None and i == 0:
            audit_stream = [(e.type, e.resource_version, e.obj.key,
                             e.obj.node_name) for e in evs]
    t_drain = time.perf_counter() - t1
    # class-plane accounting over the drain window (cumulative core
    # counters; the subtraction isolates this cell's copy-out phase)
    stats_after = store.watch_plane_state()
    n_live_classes = len(stats_after["classes"])
    drain_bytes_served = (stats_after["bytes_served"]
                          - stats_before["bytes_served"])
    drain_materializations = (stats_after["materializations"]
                              - stats_before["materializations"])
    drain_shared_hits = (stats_after["shared_hits"]
                         - stats_before["shared_hits"])
    # reference: the per-pod verb shape (serial bind_pod + its record
    # construction + per-record create, watchers still attached — the
    # same work per write the wave loop timed) measured IN THE SAME RUN,
    # so the floor check can normalize against whatever CPU
    # quota/throttle this box is under right now (absolute writes/s here
    # swing 3-4x run to run with cgroup credits)
    ref_n = min(n_pods, 1024) if n_watchers <= 1024 else 0
    # fresh unbound pods for the serial reference (the rv-CAS bind would
    # refuse re-binding the wave pods); created OUTSIDE the timed loop
    for j in range(ref_n):
        store.create(PODS, Pod(
            name=f"ref-{j}", labels={"app": "commit"},
            containers=(Container.make(
                name="c", requests={"cpu": 100, "memory": 500 * MI}),)))
    ref_pods = {p.key: p for p in store.list(PODS)[0]
                if p.name.startswith("ref-")}
    t2 = time.perf_counter()
    for j in range(ref_n):
        k = f"default/ref-{j}"
        store.bind_pod(k, "ref")
        rec = recorder.make_pod_records([
            (ref_pods[k], "Normal", "Scheduled",
             f"Successfully assigned {k} to ref")])[0]
        store.create(EVENTS, rec, move=True)
    t_ref = time.perf_counter() - t2
    for w in watches:
        w.stop()
    if audit is not None:
        audit.append(audit_stream)
    copyout_rate = round(delivered / t_drain, 1) if t_drain else 0.0
    return {
        "writes_per_s": round(writes / elapsed, 1) if elapsed else 0.0,
        "events_per_s": copyout_rate,
        "serial_writes_per_s": (round(2 * ref_n / t_ref, 1)
                                if ref_n and t_ref else None),
        "writes": writes,
        "events_delivered": delivered,
        "waves": waves,
        "watchers": n_watchers,
        "subscription_classes": n_live_classes,
        "copyout_events_per_sec": copyout_rate,
        "copyout_bytes_per_sec": (round(drain_bytes_served / t_drain, 1)
                                  if t_drain else 0.0),
        "copyout_bytes": drain_bytes_served,
        "copyout_materializations": drain_materializations,
        "copyout_shared_hits": drain_shared_hits,
        "shared_watch_classes": store.shared_watch_classes,
        "impl": store.core_impl,
    }


def run_benchmark_cell(workload: str, nodes: int, existing: int,
                       pods: int = 1000, use_tpu: bool = True,
                       burst: int = 1024) -> PerfResult:
    return run(PerfConfig(nodes=nodes, existing_pods=existing, pods=pods,
                          workload=workload, use_tpu=use_tpu, burst=burst))


def run_e2e_density(n_nodes: int = 50, n_pods: int = 150,
                    use_tpu: bool = True, node_churn: bool = False) -> dict:
    """e2e scalability density analog (test/e2e/scalability/density.go):
    pods created through the FULL cluster-in-a-process pipeline (apiserver
    admission -> scheduler -> hollow kubelets running them), reporting
    cluster-wide saturation throughput (SLO >= 8 pods/s, density.go:58) and
    pod startup latency percentiles against the <= 5s SLO
    (density.go:56,987-992). Startup = create time -> observed Running.

    `node_churn=True` is the round-14 soak ingredient (ROADMAP item 5's
    "node drains + evictions" lane): one node is DELETED at half-load
    while the scheduler is saturated — in-flight decisions referencing it
    refuse stale and replan — and re-added shortly after; the SLOs must
    hold through the churn and the report carries the refusal count."""
    import time as _t
    from kubernetes_tpu.cmd.cluster import Cluster
    from kubernetes_tpu.api.types import Pod, Container
    from kubernetes_tpu.models.hollow import MI
    from kubernetes_tpu.obs.ledger import LEDGER
    from kubernetes_tpu.scheduler import STALE_BINDS
    from kubernetes_tpu.store.store import NODES, NotFoundError
    LEDGER.reset()   # scope the decomposition to this density run
    stale0 = STALE_BINDS.value
    churn_report = None
    with Cluster(n_nodes=n_nodes, api_port=-1, use_tpu=use_tpu,
                 kubelet_interval=0.02) as cluster:
        created: dict[str, float] = {}
        started: dict[str, float] = {}
        t0 = _t.perf_counter()
        victim = None
        for j in range(n_pods):
            p = Pod(name=f"density-{j}", labels={"app": "density"},
                    containers=(Container.make(
                        name="c", requests={"cpu": 100, "memory": 200 * MI}),))
            cluster.store.create(PODS, p)
            created[p.key] = _t.perf_counter()
            if node_churn and j == n_pods // 2:
                # node death at half-load, while the scheduler is mid-drain
                nodes = sorted(n.name for n in cluster.store.list(NODES)[0])
                victim = nodes[len(nodes) // 2]
                victim_obj = cluster.store.get(NODES, victim)
                try:
                    cluster.store.delete(NODES, victim)
                except NotFoundError:
                    victim_obj = None
        if node_churn and victim is not None and victim_obj is not None:
            _t.sleep(0.2)   # let in-flight launches observe the death
            restored = victim_obj.clone()
            restored.resource_version = 0
            cluster.store.create(NODES, restored)
            churn_report = {"victim": victim, "restored": True}

        def all_running():
            pods, _rv = cluster.store.list(PODS)
            now = _t.perf_counter()
            running = 0
            for p in pods:
                if p.phase == "Running":
                    running += 1
                    started.setdefault(p.key, now)
            return running >= n_pods
        ok = cluster.wait_for(all_running, timeout=120)
        elapsed = _t.perf_counter() - t0
    lats = sorted(started[k] - created[k] for k in started)
    pct = lambda q: lats[min(len(lats) - 1, int(q * len(lats)))] if lats else None
    led = LEDGER.snapshot()
    return {
        "saturated": ok,
        "throughput": round(n_pods / elapsed, 1) if elapsed else 0.0,
        "startup_p50": round(pct(0.50), 3) if lats else None,
        "startup_p99": round(pct(0.99), 3) if lats else None,
        "startup_slo_5s": bool(lats) and pct(0.99) <= 5.0,
        "throughput_slo_8pps": (n_pods / elapsed) >= 8.0 if elapsed else False,
        # the ledger's view of the same run: scheduling (enqueue->commit)
        # percentiles + the full per-phase decomposition — "where did my
        # 5 seconds go" for the density SLO
        "sched_startup_p50": led["startup_p50"],
        "sched_startup_p99": led["startup_p99"],
        # windowed twins (trailing 30 s) beside the cumulative numbers:
        # a stall in the run's last seconds moves these while the
        # cumulative percentiles still average it away
        "sched_startup_p50_windowed": led["startup_p50_windowed"],
        "sched_startup_p99_windowed": led["startup_p99_windowed"],
        "sched_slo_ok_windowed": led["startup_slo_ok_windowed"],
        "sched_slo_burn_rate": led["slo_burn_rate"],
        "sched_phase_split": led["phase_split"],
        "node_churn": (dict(churn_report,
                            stale_refusals=int(STALE_BINDS.value - stale0))
                       if churn_report is not None else None),
    }
