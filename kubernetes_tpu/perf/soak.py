"""Soak matrix cell — the million-object steady-state scoreboard.

ROADMAP item 1: every instrument exists (ledger, flight recorder,
chaos seams, serve arrivals, active-active fleet, scheduling profiles,
the round-20 shared watch plane) but nothing had composed them into ONE
sustained run and asked "what falls over first?". `run_soak_cell` is
that composition:

    fleet mode (N instances, partitioned claims, fenced binds)
  x mixed profiles (default + a batch profile; pods carry
    spec.schedulerName, instances serve only their own)
  x serve arrivals (ArrivalGenerator through one fleet-wide
    backpressure gate)
  x steady-state churn at production-plausible rates:
      - a completion reaper (workloads finish),
      - rolling updates (delete K bound + recreate K with a new
        revision label),
      - node drains through the REAL zone-paced evictor
        (NodeLifecycleController: Ready=False -> taints -> PDB-guarded
        evictions),
      - gang arrivals (small PodGroups, all-or-nothing),
      - HPA oscillation (a cohort tracking a sinusoidal replica
        target — the hollow stand-in for a horizontal autoscaler),
      - chaos at low rates (watch drops, fan-out faults, device fetch
        faults, a bounded number of lease losses)
  x 10k-100k live watchers sharing subscription classes (half
    consuming the object stream, half the serialize-once byte ring)

with the time-series scraper (obs.timeseries.SCRAPER) sampling the
whole registry throughout and the verdict engine reading the result.
The SOAK artifact carries config + full trajectories + every verdict +
the audit results; the bench JSON line carries the summary.

The audits are the fleet/serve cells' composed: every arrival bound or
accounted (in-store + observed deletions == created, zero unbound at
settle), zero double-binds (BindAuditor), per-profile claim
disjointness, and a post-run parity pass (flight-recorder replay of
fresh windows through instance 0 against the serial oracle).

Million-object arithmetic (the 100k-watcher matrix cell): 2k nodes +
~120s x 2k arrivals/s ~= 240k pod objects through the store, ~480k
bind/delete events, each fanned to 100k watchers through ~64 classes
= O(10^10) watcher-event deliveries collapsed to O(10^5) per-event
materializations by the class plane — the scoreboard proves the plane
holds that compression under churn, chaos, and drains at once.
"""
from __future__ import annotations

import json
import math
import threading
import time
import zlib
from collections import deque
from typing import Optional

import numpy as np

from kubernetes_tpu import chaos, obs
from kubernetes_tpu.store.store import (
    Store, BackpressureError, ConflictError, ExpiredError, MODIFIED,
    DELETED, NODES, NotFoundError, PODS, PODGROUPS,
)

GI = 1024 ** 3
MI = 1024 ** 2

#: the non-default profile of the mixed-profile soak: a batch-packing
#: scoring vector (bin-pack over spread) — names from TPU_WEIGHT_KEYS
SOAK_BATCH_PROFILE = "soak-batch"


def _mknode(i: int):
    from kubernetes_tpu.api.types import Node, NodeCondition
    return Node(
        name=f"node-{i}",
        labels={"failure-domain.beta.kubernetes.io/zone": f"zone-{i % 3}",
                "kubernetes.io/hostname": f"node-{i}"},
        allocatable={"cpu": 4000, "memory": 32 * GI, "pods": 110},
        # a Ready condition from the start: the drain actor flips it and
        # the node-lifecycle controller grades/taints off it
        conditions=(NodeCondition(type="Ready", status="True"),))


def run_soak_cell(n_nodes: int = 2000, duration: float = 60.0,
                  arrival_rate: float = 1500.0, instances: int = 2,
                  watchers: int = 10_000, watch_classes: int = 64,
                  window: int = 2048, depth: int = 3,
                  use_tpu: bool = True, seed: int = 0,
                  scrape_interval: float = 0.5,
                  soak_out: Optional[str] = None,
                  gang_every: float = 4.0, gang_size: int = 4,
                  roll_every: float = 2.0, roll_batch: int = 16,
                  drain_nodes: int = 8, eviction_rate: float = 20.0,
                  hpa_period: float = 20.0, hpa_base: int = 64,
                  hpa_amp: int = 48,
                  chaos_rates: Optional[dict] = None,
                  parity_pods: int = 128,
                  max_resident: Optional[int] = None) -> dict:
    """One soak cell (module docstring); returns the summary dict the
    bench prints and (with `soak_out`) writes the full SOAK artifact."""
    from kubernetes_tpu.api.types import Container, Pod
    from kubernetes_tpu.apiserver.server import wire_line
    from kubernetes_tpu.controllers.nodelifecycle import (
        NodeLifecycleController)
    from kubernetes_tpu.coscheduling.types import LABEL_POD_GROUP, PodGroup
    from kubernetes_tpu.fleet import BindAuditor, FleetInstance, shard_of
    from kubernetes_tpu.obs import flight as obs_flight
    from kubernetes_tpu.obs.ledger import LEDGER
    from kubernetes_tpu.obs.timeseries import SCRAPER, evaluate_verdicts
    from kubernetes_tpu.profiles import (
        DEFAULT_PROFILE_NAME, ProfileSet, SchedulingProfile)
    from kubernetes_tpu.serve import ArrivalGenerator
    from kubernetes_tpu.serve.backpressure import fleet_gate

    instances = max(1, int(instances))
    n_shards = max(8, 4 * instances)
    store = Store(watch_log_size=1 << 18)
    store.set_wire_encoder(wire_line)
    for i in range(n_nodes):
        store.create(NODES, _mknode(i))

    # -- mixed profiles + fleet ---------------------------------------------
    pset = ProfileSet([
        SchedulingProfile(name=DEFAULT_PROFILE_NAME),
        SchedulingProfile(name=SOAK_BATCH_PROFILE, weights=(
            ("BalancedResourceAllocation", 1),
            ("MostRequestedPriority", 2),
            ("TaintTolerationPriority", 1),
        )),
    ])
    prof_names = [DEFAULT_PROFILE_NAME, SOAK_BATCH_PROFILE]
    inst_profiles = [prof_names[i % len(prof_names)]
                     for i in range(instances)]
    # only profiles with a live instance may appear on a pod: an unknown
    # (or unserved) schedulerName is REPORTED, never scheduled, and the
    # settle audit would hang on it
    served_profiles = sorted(set(inst_profiles))
    idents = [f"soak-sched-{i}" for i in range(instances)]
    # claims partition per PROFILE: an instance's peer set is the
    # instances serving the SAME profile
    peers_of = {p: [idents[i] for i in range(instances)
                    if inst_profiles[i] == p] for p in served_profiles}
    fleet = [FleetInstance(store, idents[i], peers_of[inst_profiles[i]],
                           profile=inst_profiles[i], profiles=pset,
                           use_tpu=use_tpu, window=window, depth=depth,
                           n_shards=n_shards, lease_duration=5.0,
                           renew_deadline=3.0,
                           percentage_of_nodes_to_score=100)
             for i in range(instances)]
    for inst in fleet:
        inst.sync()

    def mkpod(name: str) -> Pod:
        h = zlib.crc32(name.encode())
        return Pod(name=name, namespace=f"ns-{h % (4 * n_shards)}",
                   labels={"app": "soak"},
                   scheduler_name=served_profiles[
                       (h >> 8) % len(served_profiles)],
                   containers=(Container.make(
                       name="c",
                       requests={"cpu": 100, "memory": 500 * MI}),))

    # warmup (ungated): jit compiles + claim settling for every profile
    warm = ArrivalGenerator(store, rate=10 ** 9, total=32 * instances,
                            pod_fn=mkpod, name_prefix="soakwarm-",
                            seed=seed)
    for _ in range(3):
        warm.tick()
        for inst in fleet:
            inst.step()

    def fleet_idle() -> bool:
        for inst in fleet:
            if inst.sched.queue.num_pending() > 0:
                return False
            if inst.sched.informers.informer(PODS).backlog() > 0:
                return False
        return True

    deadline_warm = time.perf_counter() + 60.0
    while time.perf_counter() < deadline_warm:
        if sum(inst.step() for inst in fleet) == 0 and fleet_idle():
            break

    # -- watcher plane -------------------------------------------------------
    # `watchers` live watches over `watch_classes` subscription classes
    # (identical (kind, selector) shares one class in the commit core);
    # odd watchers consume the serialize-once byte ring, even ones the
    # object stream. Drained in rotating slices; a watcher the ring
    # expired is STICKY-dropped (round-20 resync contract) and counted.
    watch_classes = max(1, min(int(watch_classes), max(1, int(watchers))))
    watch_pool = [store.watch(PODS, selector=f"wc{i % watch_classes}")
                  for i in range(int(watchers))]
    expired_watchers = 0
    rotate_at = 0
    slice_size = max(64, int(watchers) // 128) if watchers else 0

    def drain_watch_slice() -> None:
        nonlocal rotate_at, expired_watchers
        if not watch_pool:
            return
        for _ in range(min(slice_size, len(watch_pool))):
            i = rotate_at % len(watch_pool)
            rotate_at += 1
            w = watch_pool[i]
            try:
                if i % 2:
                    w.drain_bytes()
                else:
                    w.drain()
            except ExpiredError:
                # sticky: ExpiredError forever -> drop from rotation
                # (classmates stay undisturbed); real consumers re-list
                w.stop()
                watch_pool.pop(i)
                expired_watchers += 1

    # -- soak gauges: watcher-lag tail + utilization ------------------------
    lag_count = obs.gauge(
        "store_watchers", "Live watchers registered on the soak store "
        "(from watcher_lag_summary — all watchers, not the 1k debug "
        "sample).")
    lag_max = obs.gauge(
        "store_watcher_backlog_max", "Largest published-but-unconsumed "
        "watcher backlog across ALL watchers (watcher_lag_summary).")
    lag_p99 = obs.gauge(
        "store_watcher_backlog_p99", "p99 watcher backlog across ALL "
        "watchers — the soak verdict engine's watcher-lag-tail input.")
    lag_count.set_function(
        lambda: float(store.watcher_lag_summary(ttl=1.0)["count"]))
    lag_max.set_function(
        lambda: float(store.watcher_lag_summary(ttl=1.0)["max"]))
    lag_p99.set_function(
        lambda: float(store.watcher_lag_summary(ttl=1.0)["p99"]))

    # utilization under the constraint mix, maintained from the
    # bookkeeper watch (binds in, deletions out) — not a store walk
    resident_bound = [0]
    cpu_capacity = float(n_nodes * 4000)
    pods_capacity = float(n_nodes * 110)
    util_cpu = obs.gauge(
        "cluster_cpu_utilization", "Requested-CPU utilization of the "
        "soak cluster under the live constraint mix (bound resident "
        "pods x request / allocatable).")
    util_pods = obs.gauge(
        "cluster_pods_utilization", "Pod-slot utilization of the soak "
        "cluster (bound resident pods / allocatable pod slots).")
    util_cpu.set_function(
        lambda: resident_bound[0] * 100.0 / cpu_capacity)
    util_pods.set_function(
        lambda: resident_bound[0] / pods_capacity)

    # -- bookkeeper watch: reaper + accounting + hpa fifo -------------------
    # cohorts the accounting audit covers (every pod this cell creates)
    prefixes = ("soak-", "roll-", "gang-", "hpa-", "soakwarm-")
    created_total = warm.stats()["created"]
    deleted_total = 0
    accounting_resynced = False
    book_watch = store.watch(PODS)
    bound_fifo: deque = deque()
    seen_bound: set = set()
    hpa_bound: deque = deque()
    reaped = 0
    cap = n_nodes * min(110, 4000 // 100)
    resident_target = (int(max_resident) if max_resident is not None
                       else max(4 * window, cap // 2))

    def _ours(name: str) -> bool:
        return name.startswith(prefixes)

    def bookkeep() -> None:
        nonlocal reaped, deleted_total, accounting_resynced
        try:
            events = book_watch.drain()
        except ExpiredError:
            # the ring expired under us (possible under chaos watch
            # drops): rebuild the resident view from a full list and
            # re-derive the deletion count from the accounting identity
            accounting_resynced = True
            bound_fifo.clear()
            seen_bound.clear()
            hpa_bound.clear()
            in_store = bound = 0
            for p in store.list(PODS)[0]:
                if not _ours(p.name):
                    continue
                in_store += 1
                if p.node_name:
                    bound += 1
                    bound_fifo.append(p.key)
                    seen_bound.add(p.key)
                    if p.name.startswith("hpa-"):
                        hpa_bound.append(p.key)
            deleted_total = max(deleted_total, created_total - in_store)
            resident_bound[0] = bound
            return
        for ev in events:
            if not _ours(ev.obj.name):
                continue
            if ev.type == MODIFIED and ev.obj.node_name \
                    and ev.obj.key not in seen_bound:
                seen_bound.add(ev.obj.key)
                resident_bound[0] += 1
                if not ev.obj.name.startswith("hpa-"):
                    bound_fifo.append(ev.obj.key)
                else:
                    hpa_bound.append(ev.obj.key)
            elif ev.type == DELETED:
                deleted_total += 1
                if ev.obj.key in seen_bound:
                    seen_bound.discard(ev.obj.key)
                    resident_bound[0] -= 1
        if len(bound_fifo) > resident_target:
            batch = []
            while len(bound_fifo) > resident_target:
                batch.append(bound_fifo.popleft())
            reaped += len(store.delete_many(PODS, batch))

    # -- churn actors --------------------------------------------------------
    # round 23: every actor flushes ONE batched verb per tick — creates
    # ride the gated create_many (429 carries `accepted`), deletes ride
    # delete_many, in-place restamps ride update_many with per-item
    # rv-CAS, and the drain flips batch through update_many with a
    # guaranteed_update fallback for CAS losers
    churn = {"rolled": 0, "roll_shed": 0, "gangs": 0, "gang_pods": 0,
             "gang_shed": 0, "hpa_up": 0, "hpa_down": 0, "hpa_shed": 0,
             "restamped": 0, "restamp_conflicts": 0,
             "drained_nodes": 0, "drain_restored": 0}

    def gated_create(pod: Pod, shed_key: str) -> bool:
        nonlocal created_total
        try:
            store.create(PODS, pod)
        except BackpressureError:
            churn[shed_key] += 1
            return False
        except ConflictError:
            return False
        created_total += 1
        return True

    def gated_create_many(pods: list, shed_key: str) -> int:
        """One gated create_many per actor tick: the gate admits a
        prefix, the 429 carries `accepted`, and the shed tail is the
        actor's loss (churn pods are synthetic — nothing retries)."""
        nonlocal created_total
        if not pods:
            return 0
        try:
            landed = len(store.create_many(PODS, pods))
        except BackpressureError as e:
            landed = int(getattr(e, "accepted", 0))
            churn[shed_key] += len(pods) - landed
        created_total += landed
        return landed

    roll_seq = [0]

    def roll_tick() -> None:
        """Rolling update: the oldest K bound pods 'roll' — one batched
        delete, one batched create carrying the next revision label."""
        k = min(roll_batch, len(bound_fifo))
        if k <= 0:
            return
        batch = [bound_fifo.popleft() for _ in range(k)]
        n = len(store.delete_many(PODS, batch))
        rev = f"r{roll_seq[0] // max(1, roll_batch)}"
        fresh = []
        for _ in range(n):
            name = f"roll-{roll_seq[0]}"
            roll_seq[0] += 1
            pod = mkpod(name)
            pod.name = name
            pod.labels = {"app": "soak", "revision": rev}
            fresh.append(pod)
        churn["rolled"] += gated_create_many(fresh, "roll_shed")

    restamp_seq = [0]

    def restamp_tick() -> None:
        """In-place revision restamp on bound pods: ONE update_many per
        tick with per-item rv-CAS — a pod the scheduler (or reaper)
        touched between the read and the write is a conflict/missing
        outcome, counted and dropped, never retried and never clobbered
        (CAS keeps the bind that raced us)."""
        k = min(roll_batch, len(bound_fifo))
        if k <= 0:
            return
        rev = f"g{restamp_seq[0]}"
        restamp_seq[0] += 1
        updates = []
        for key in list(bound_fifo)[-k:]:      # newest bound: least
            try:                               # likely mid-reap
                cur = store.get(PODS, key)
            except NotFoundError:
                continue
            cur.labels = dict(cur.labels)
            cur.labels["restamp"] = rev
            updates.append((cur, cur.resource_version))
        if not updates:
            return
        confl: list = []
        miss: list = []
        out = store.update_many(PODS, updates, conflicts=confl,
                                missing=miss)
        churn["restamped"] += len(out)
        churn["restamp_conflicts"] += len(confl) + len(miss)

    gang_seq = [0]

    def gang_tick() -> None:
        """Gang arrival: one PodGroup of `gang_size` spec-identical
        members, all in ONE namespace (one instance owns the gang) on
        the default profile — scheduled all-or-nothing."""
        g = gang_seq[0]
        gang_seq[0] += 1
        gname = f"gang-{seed}-{g}"
        ns = f"ns-{(g * 7) % (4 * n_shards)}"
        try:
            store.create(PODGROUPS, PodGroup(name=gname,
                                             min_member=gang_size))
        except ConflictError:
            return
        placed = 0
        for r in range(gang_size):
            pod = Pod(name=f"{gname}-r{r}", namespace=ns,
                      labels={LABEL_POD_GROUP: gname, "app": "gang"},
                      scheduler_name=DEFAULT_PROFILE_NAME,
                      containers=(Container.make(
                          name="c",
                          requests={"cpu": 100, "memory": 500 * MI}),))
            if gated_create(pod, "gang_shed"):
                placed += 1
        churn["gangs"] += 1
        churn["gang_pods"] += placed

    hpa_seq = [0]
    t_start = [0.0]

    def hpa_tick(now: float) -> None:
        """HPA oscillation (hollow stand-in for a horizontal
        autoscaler): the 'hpa-' cohort tracks a sinusoidal replica
        target — scale-ups are gated creates, scale-downs delete the
        newest bound members."""
        phase = 2.0 * math.pi * (now - t_start[0]) / hpa_period
        target = int(hpa_base + hpa_amp * math.sin(phase))
        current = len(hpa_bound)
        if current < target:
            fresh = []
            for _ in range(min(target - current, 32)):
                name = f"hpa-{hpa_seq[0]}"
                hpa_seq[0] += 1
                pod = mkpod(name)
                pod.name = name
                fresh.append(pod)
            churn["hpa_up"] += gated_create_many(fresh, "hpa_shed")
        elif current > target:
            batch = [hpa_bound.pop()
                     for _ in range(min(current - target, 32))]
            churn["hpa_down"] += len(store.delete_many(PODS, batch))

    # node drains through the real zone-paced evictor: the controller
    # monitors Ready conditions, taints, and drains each flipped node's
    # pods through the PDB-guarded eviction subresource at
    # `eviction_rate`/s per zone (rate scaled for the compressed soak)
    lifecycle = NodeLifecycleController(
        store, eviction_rate=eviction_rate,
        secondary_eviction_rate=eviction_rate / 10.0)
    lifecycle.sync()
    drained: list = []
    drain_window = (0.35 * duration, 0.70 * duration)

    def flip_ready(name: str, status: str) -> None:
        from kubernetes_tpu.api.types import NodeCondition

        def mutate(n):
            n.conditions = (NodeCondition(type="Ready", status=status),)
            return n
        store.guaranteed_update(NODES, name, mutate)

    def flip_ready_many(names: list, status: str) -> None:
        """All of a drain wave's Ready flips in ONE update_many with
        per-node rv-CAS; a CAS loser (the lifecycle controller tainting
        the same node concurrently) falls back to guaranteed_update —
        last-writer-wins would silently clobber its taints."""
        from kubernetes_tpu.api.types import NodeCondition
        updates = []
        for name in names:
            try:
                node = store.get(NODES, name)
            except NotFoundError:
                continue
            node.conditions = (NodeCondition(type="Ready",
                                             status=status),)
            updates.append((node, node.resource_version))
        confl: list = []
        store.update_many(NODES, updates, conflicts=confl)
        for key in confl:
            flip_ready(key, status)

    def drain_tick(now: float) -> None:
        rel = now - t_start[0]
        if not drained and rel >= drain_window[0] and drain_nodes > 0:
            # drain a zone-0 slice: Ready=False -> the controller taints
            # NoSchedule+NoExecute and zone-paces the evictions
            for i in range(0, 3 * drain_nodes, 3):
                if i >= n_nodes:
                    break
                drained.append(f"node-{i}")
            flip_ready_many(drained, "False")
            churn["drained_nodes"] = len(drained)
        elif drained and churn["drain_restored"] == 0 \
                and rel >= drain_window[1]:
            flip_ready_many(drained, "True")
            churn["drain_restored"] = len(drained)

    # pre-touch the fence-conflict children (inc(0) creates the child
    # without moving it): labeled families with no children are absent
    # from the scraper's series, and the fence-spike detector would
    # read "no fleet live" when the truth is "fleet ran, zero conflicts"
    from kubernetes_tpu.fleet import BIND_CONFLICTS
    from kubernetes_tpu.store.store import FENCED_WRITES
    for outcome in ("requeued", "fenced"):
        BIND_CONFLICTS.labels(outcome).inc(0)
    for verb in ("commit_wave", "bind"):
        FENCED_WRITES.labels(verb).inc(0)

    # -- chaos plan (production-plausible rates) ----------------------------
    rates = dict(chaos_rates) if chaos_rates else {
        "store.fanout": 1.0 / 5000.0,
        "watch.drop": 1.0 / 2000.0,
        "device.fetch": 1.0 / 5000.0,
        "fleet.lease-loss": 1.0 / 2000.0,
    }
    chaos.plan(seed=seed, rates=rates,
               limits={"fleet.lease-loss": 2})

    # -- the timed soak ------------------------------------------------------
    # round-23 churn-plane instrument: the batch-verb counters are
    # process-cumulative — the cell reports (and asserts on) its DELTA
    from kubernetes_tpu.store.store import (
        BATCH_MUTATION_CALLS, BATCH_MUTATIONS)
    _batch_verbs = ("update_many", "delete_many", "evict_many")
    batch_base = {v: (int(BATCH_MUTATION_CALLS.labels(v).value),
                      int(BATCH_MUTATIONS.labels(v).value))
                  for v in _batch_verbs}
    auditor = BindAuditor(store)
    gate = fleet_gate([inst.loop for inst in fleet],
                      max_depth=max(4 * window, int(2 * arrival_rate)))
    store.admission_gate = gate
    LEDGER.reset()
    # ring must hold the soak AND the settle tail — newest-N eviction
    # dropping the run's first minutes would blind every trend detector
    n_samples_target = int((duration + 150.0) / scrape_interval) + 64
    SCRAPER.reset(capacity=max(720, n_samples_target),
                  interval=scrape_interval)
    SCRAPER.start()
    gen = ArrivalGenerator(store, rate=arrival_rate, pod_fn=mkpod,
                           name_prefix="soak-", seed=seed)
    stop = threading.Event()

    def drive(inst: FleetInstance) -> None:
        while not stop.is_set():
            if inst.step() == 0:
                time.sleep(0.001)

    threads = [threading.Thread(target=drive, args=(inst,), daemon=True,
                                name=f"soak-{inst.identity}")
               for inst in fleet]
    partition_overlap = False
    bound0 = sum(inst.loop.pods_bound for inst in fleet)
    t0 = time.perf_counter()
    t_start[0] = t0
    for th in threads:
        th.start()
    next_roll = t0 + roll_every
    next_gang = t0 + gang_every
    next_hpa = t0 + 1.0
    next_restamp = t0 + 1.0
    next_pump = t0 + 0.25
    next_probe = t0 + 0.5
    t_end = t0 + duration
    now = t0
    while now < t_end:
        bookkeep()
        gen.tick()
        drain_watch_slice()
        if now >= next_roll:
            roll_tick()
            next_roll = now + roll_every
        if now >= next_gang and gang_size > 0:
            gang_tick()
            next_gang = now + gang_every
        if now >= next_hpa and hpa_amp > 0:
            hpa_tick(now)
            next_hpa = now + 1.0
        if now >= next_restamp:
            restamp_tick()
            next_restamp = now + 1.0
        if now >= next_pump:
            drain_tick(now)
            lifecycle.pump()
            next_pump = now + 0.25
        if now >= next_probe:
            auditor.scan()
            # obs delta-sync: the commit core counts materializations /
            # shared hits monotonically; watch_plane_state() folds the
            # deltas into the process counters the scraper samples —
            # without this call the copy-out rate series never moves
            store.watch_plane_state()
            # claims must stay disjoint WITHIN a profile (two profiles
            # legitimately own the same namespace shard)
            for prof in served_profiles:
                seen: set = set()
                for i, inst in enumerate(fleet):
                    if inst_profiles[i] != prof:
                        continue
                    owned = inst.claims.owned()
                    if owned & seen:
                        partition_overlap = True
                    seen |= owned
            next_probe = now + 0.5
        time.sleep(0.002)
        now = time.perf_counter()
    elapsed = time.perf_counter() - t0
    aggregate = (sum(inst.loop.pods_bound for inst in fleet) - bound0) \
        / elapsed if elapsed else 0.0

    # -- settle: arrivals + churn stop; everything admitted must bind -------
    chaos.disable()
    if drained:                         # no node may stay cordoned
        flip_ready_many(drained, "True")
    settle_deadline = time.perf_counter() + 90.0
    idle_polls = 0
    while time.perf_counter() < settle_deadline:
        gen.flush_retries(timeout=0.2)
        bookkeep()
        drain_watch_slice()
        lifecycle.pump()
        auditor.scan()
        if gen.stats()["pending_retry"] == 0 and fleet_idle():
            idle_polls += 1
            if idle_polls >= 3:
                break
        else:
            idle_polls = 0
        time.sleep(0.05)
    stop.set()
    for th in threads:
        th.join(timeout=5.0)
    drain_deadline = time.perf_counter() + 30.0
    while not fleet_idle() and time.perf_counter() < drain_deadline:
        bookkeep()
        for inst in fleet:
            inst.step()
    auditor.scan()
    SCRAPER.stop()
    led = LEDGER.snapshot()
    lag_summary = store.watcher_lag_summary(ttl=0)

    # -- audits --------------------------------------------------------------
    g = gen.stats()
    created_total += g["created"]
    bookkeep()
    measured = [p for p in store.list(PODS)[0] if _ours(p.name)]
    unbound = sum(1 for p in measured if not p.node_name)
    audit_accounting = (len(measured) + deleted_total == created_total)
    assert audit_accounting or accounting_resynced, \
        (f"soak accounting leak: {len(measured)} in store + "
         f"{deleted_total} deleted != {created_total} created")
    assert unbound == 0, f"{unbound} admitted pods never bound at settle"
    assert not auditor.violations, \
        f"DOUBLE BINDS observed: {auditor.violations[:5]}"
    assert not partition_overlap, \
        "live claims overlapped within a profile"

    # -- parity: replay fresh windows through instance 0 --------------------
    inst0 = fleet[0]
    owned = inst0.claims.owned()
    par_namespaces = [f"ns-{i}" for i in range(4 * n_shards)
                      if shard_of(f"ns-{i}", n_shards) in owned]
    violations: list = []
    if par_namespaces and parity_pods > 0:
        from kubernetes_tpu.api.types import Container as _C, Pod as _P
        par_i = [0]

        def par_pod(name: str) -> Pod:
            ns = par_namespaces[par_i[0] % len(par_namespaces)]
            par_i[0] += 1
            return _P(name=name, namespace=ns, labels={"app": "par"},
                      scheduler_name=inst0.profile,
                      containers=(_C.make(
                          name="c",
                          requests={"cpu": 100, "memory": 500 * MI}),))

        obs_flight.RECORDER.configure(mode="replay", capacity=8)
        obs_flight.RECORDER.clear()
        par = ArrivalGenerator(store, rate=10 ** 9, total=parity_pods,
                               pod_fn=par_pod, name_prefix="par-",
                               seed=seed + 1)
        try:
            while not par.finished():
                par.tick()
                inst0.step()
            inst0.loop.drain(timeout=30.0)
            violations = obs_flight.RECORDER.replay_all()
        finally:
            obs_flight.RECORDER.configure(mode="digest")
            obs_flight.RECORDER.clear()

    # -- teardown ------------------------------------------------------------
    book_watch.stop()
    auditor.stop()
    for w in watch_pool:
        w.stop()
    store.admission_gate = None
    # drop the cell's store/deque refs from the process-global gauges
    for gfam in (lag_count, lag_max, lag_p99, util_cpu, util_pods):
        gfam.set_function(lambda: 0.0)

    # -- scoreboard: series + verdicts + artifact ---------------------------
    report = evaluate_verdicts(SCRAPER)
    doc = SCRAPER.series()
    sampled = sorted(doc["families"])

    # round 23: churn mutations must land as BATCHED verbs — the counter
    # delta is the proof (O(batches) store-lock acquisitions, not
    # O(pods)); the eviction lane is drain-gated, the others always run
    batch_lane = {}
    for verb in _batch_verbs:
        calls = int(BATCH_MUTATION_CALLS.labels(verb).value) \
            - batch_base[verb][0]
        objs = int(BATCH_MUTATIONS.labels(verb).value) \
            - batch_base[verb][1]
        batch_lane[verb] = {"calls": calls, "objects": objs}
    if duration >= 10.0:
        assert batch_lane["update_many"]["calls"] > 0, \
            "churn restamps/flips never rode update_many"
        assert batch_lane["delete_many"]["calls"] > 0, \
            "rolls/reaps never rode delete_many"

    # packing lane: the cpu child of cluster_resource_utilization (the
    # scheduler-snapshot fill gauge, round 22) — children must NOT be
    # summed (SeriesView.col would blend cpu+memory+slots)
    packing = {"samples": 0, "mean": None, "max": None}
    _fam = doc["families"].get("cluster_resource_utilization")
    if _fam is not None:
        _vals = [float(v)
                 for v in _fam["series"].get('resource="cpu"', {})
                 .get("value", ()) or ()
                 if v is not None and not math.isnan(float(v))]
        if _vals:
            packing = {"samples": len(_vals),
                       "mean": round(sum(_vals) / len(_vals), 4),
                       "max": round(max(_vals), 4)}
    required = {
        "windowed_startup_p99": "pod_startup_seconds_p99_windowed",
        "rate_series": "serve_pods_scheduled_total",
        "process_self_metric": "process_resident_memory_bytes",
    }
    summary = {
        "nodes": n_nodes,
        "instances": instances,
        "profiles": served_profiles,
        "arrival_rate": arrival_rate,
        "duration": round(elapsed, 2),
        "aggregate_pods_per_s": round(aggregate, 1),
        "watchers": int(watchers),
        "watch_classes": int(watch_classes),
        "watchers_expired": expired_watchers,
        "watcher_lag_summary": lag_summary,
        "startup_p50": led["startup_p50"],
        "startup_p99": led["startup_p99"],
        "startup_p50_windowed": led["startup_p50_windowed"],
        "startup_p99_windowed": led["startup_p99_windowed"],
        "startup_slo_ok": led["startup_slo_ok"],
        "startup_slo_ok_windowed": led["startup_slo_ok_windowed"],
        "slo_burn_rate": led["slo_burn_rate"],
        "pods_created": created_total,
        "pods_deleted": deleted_total,
        "workload_reaped": reaped,
        "churn": churn,
        "batch_mutations": batch_lane,
        "packing_utilization": packing,
        "arrivals": g,
        "chaos_injections": {
            s: chaos.INJECTIONS.labels(s).value for s in chaos.SEAMS},
        "timeseries_samples": doc["samples"],
        "timeseries_families": len(sampled),
        "required_families": {k: (v in sampled)
                              for k, v in required.items()},
        "verdicts": [v["verdict"] for v in report["verdicts"]],
        "verdicts_evaluated": len(report["verdicts"]),
        "first_failure": report["first_failure"],
        "parity_violations": len(violations),
        "parity_violation_samples": violations[:3],
        "double_binds": len(auditor.violations),
        "partition_disjoint": not partition_overlap,
        "accounting_resynced": accounting_resynced,
        "audit_all_admitted_or_accounted": True,   # asserted above
        "audit_no_double_bind": True,
    }
    if soak_out:
        artifact = {
            "config": {
                "nodes": n_nodes, "duration": duration,
                "arrival_rate": arrival_rate, "instances": instances,
                "watchers": int(watchers),
                "watch_classes": int(watch_classes),
                "scrape_interval": scrape_interval, "seed": seed,
                "chaos_rates": rates,
            },
            "summary": {k: v for k, v in summary.items()
                        if k != "parity_violation_samples"},
            "ledger": led,
            "verdict_report": report,
            "timeseries": doc,
        }
        with open(soak_out, "w") as f:
            json.dump(artifact, f, sort_keys=True)
        summary["soak_artifact"] = soak_out
    return summary
