"""Scheduling profiles — a multi-profile `KubeSchedulerConfiguration`
(round 19; ROADMAP item 4's per-tenant scoring lanes).

One scheduler process serves several named profiles: a pod picks its
profile by `spec.schedulerName` (the reference's multi-profile contract,
kube-scheduler KubeSchedulerConfiguration.profiles), and each profile
carries its OWN priority-weight vector — Gavel-style per-tenant
throughput-aware weights (PAPERS.md 2008.09213) without per-tenant
scheduler processes. On device the vectors stack into ONE dense
`[profiles x priorities]` int64 tensor (column order =
`ops.kernels.PRIORITY_AXIS`); every kernel core gathers each pod's weight
row by its `profile_id` (a PodRowCache column filled at admission), so a
single launch scores a window that mixes tenants — the tensor rides the
upload once and stays resident.

The last tensor column is `gang_locality`: the rank-aware gang
set-scoring objective (PAPERS.md 2603.22691 — MPI ranks want zone/ICI
locality). A profile with `rank_aware=True` gives its gangs a
device-scored preference for packing the group into few zones: inside
the fused segment scan, each placed member one-hot-folds its node's zone
into a per-segment count vector, and later members of the SAME gang
score every node by `min(members_already_in_zone, 10) * gang_weight` —
candidate node SETS, not just nodes, via the same one-hot zone
reductions the spread kernel uses. The serial referee
(oracle.gang.GangTrial + oracle.priorities.gang_locality_map) computes
the identical objective, so per-profile decisions stay oracle-parity.
The default profile ships with `rank_aware=False` and the provider
weight vector — bit-identical to the pre-profile scheduler.

Validation rides the existing `apis/policy` bounds: every weight
positive and < MAX_WEIGHT (weight * MaxPriority must fit int32),
duplicate profile names and unknown priority names are errors.

A pod whose `spec.schedulerName` no profile claims is REPORTED
(`scheduler_profile_unknown_total` + a FailedScheduling-style event),
never silently scored by the default profile.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kubernetes_tpu import obs
from kubernetes_tpu.apis.policy import (
    MAX_WEIGHT, Policy, PolicyValidationError, PriorityPolicy,
    validate_policy,
)

DEFAULT_PROFILE_NAME = "default-scheduler"

PROFILE_UNKNOWN = obs.counter(
    "scheduler_profile_unknown_total",
    "Pods whose spec.schedulerName matched no configured scheduling "
    "profile — reported (counter + event), never silently scored by the "
    "default profile.")
PROFILE_SCHEDULED = obs.counter(
    "scheduler_profile_scheduled_total",
    "Pods successfully scheduled, by the profile that scored them.",
    ("profile",))


def _kernel_priority_names() -> dict:
    """K8s priority name -> kernel weight key (the device-supported set —
    a profile's weights must all be kernel-expressible so the tensor can
    score every profile in one launch)."""
    from kubernetes_tpu.factory import TPU_WEIGHT_KEYS
    return TPU_WEIGHT_KEYS


@dataclass(frozen=True)
class SchedulingProfile:
    """One named profile: a priority-weight vector + the rank-aware knob.

    `weights` maps reference priority names (e.g. "LeastRequestedPriority")
    to integer weights; an empty mapping means the DefaultProvider vector
    (factory.DEFAULT_PRIORITY_WEIGHTS) — exactly today's scoring.
    `rank_aware` switches on gang set-scoring for this profile's
    PodGroups, weighted by `gang_weight`."""
    name: str
    weights: tuple = ()          # ((priority name, weight), ...)
    rank_aware: bool = False
    gang_weight: int = 1

    def name_weights(self) -> dict:
        if self.weights:
            return dict(self.weights)
        from kubernetes_tpu.factory import DEFAULT_PRIORITY_WEIGHTS
        return dict(DEFAULT_PRIORITY_WEIGHTS)

    @staticmethod
    def from_dict(d: dict) -> "SchedulingProfile":
        """Accepts the KubeSchedulerConfiguration-flavored shape:
        {"schedulerName": ..., "priorities": {name: weight} | [{"name":
        ..., "weight": ...}], "rankAwareGang": bool, "gangWeight": int}
        (snake_case twins accepted)."""
        name = d.get("schedulerName") or d.get("scheduler_name") \
            or d.get("name") or DEFAULT_PROFILE_NAME
        prios = d.get("priorities") or ()
        if isinstance(prios, dict):
            weights = tuple(sorted(prios.items()))
        else:
            weights = tuple(sorted(
                (p["name"], p.get("weight", 1)) for p in prios))
        return SchedulingProfile(
            name=name, weights=weights,
            rank_aware=bool(d.get("rankAwareGang",
                                  d.get("rank_aware", False))),
            gang_weight=int(d.get("gangWeight", d.get("gang_weight", 1))))


class ProfileValidationError(PolicyValidationError):
    pass


class ProfileSet:
    """An ordered, validated set of scheduling profiles.

    Profile 0 is the DEFAULT profile (index 0 in the weight tensor); a
    single default-vector, non-rank-aware profile degenerates to the
    pre-profile scheduler (`tensor_mode()` False — callers keep the
    exact old kernel programs)."""

    def __init__(self, profiles: Optional[list] = None,
                 validate: bool = True):
        if not profiles:
            profiles = [SchedulingProfile(DEFAULT_PROFILE_NAME)]
        self.profiles: list[SchedulingProfile] = list(profiles)
        self._index = {p.name: i for i, p in enumerate(self.profiles)}
        #: uids already reported unknown (bounds event/counter noise)
        self._unknown_seen: set = set()
        self.unknown_names: dict[str, int] = {}
        #: per-profile scheduled counts (the /debug/sched section's copy;
        #: the obs counter is the wire-visible one)
        self.scheduled_counts = [0] * len(self.profiles)
        #: bumped on every successful set_row — serving caches key their
        #: refresh off it (round 22: the tuner's write path)
        self.version = 0
        if validate:
            self.validate()

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_dict(d: dict) -> "ProfileSet":
        return ProfileSet([SchedulingProfile.from_dict(p)
                           for p in d.get("profiles", ())])

    @staticmethod
    def from_json(text: str) -> "ProfileSet":
        return ProfileSet.from_dict(json.loads(text))

    @staticmethod
    def from_file(path: str) -> "ProfileSet":
        with open(path) as f:
            return ProfileSet.from_dict(json.load(f))

    # -- validation (apis/policy bounds) -------------------------------------
    def validate(self) -> None:
        """Duplicate profile names and unknown priority names are errors;
        every weight (including rank-aware gang weights) rides the
        existing positive/MAX_WEIGHT policy bounds."""
        errs = []
        seen: set = set()
        known = _kernel_priority_names()
        for p in self.profiles:
            if p.name in seen:
                errs.append(f"duplicate profile name {p.name!r}")
            seen.add(p.name)
            if not p.name:
                errs.append("profile name must not be empty")
            nw = p.name_weights()
            for prio_name in nw:
                if prio_name not in known:
                    errs.append(f"profile {p.name}: unknown priority "
                                f"{prio_name!r}")
            pol = Policy(priorities=[
                PriorityPolicy(name=n, weight=w) for n, w in
                sorted(nw.items())])
            if p.rank_aware:
                pol.priorities.append(PriorityPolicy(
                    name=f"{p.name}/GangLocalityPriority",
                    weight=p.gang_weight))
            try:
                validate_policy(pol)
            except PolicyValidationError as e:
                errs.append(f"profile {p.name}: {e}")
        if errs:
            raise ProfileValidationError("; ".join(errs))

    # -- row updates (round 22: the tuner's write path) ----------------------
    def set_row(self, name_or_index, weights, rank_aware=None,
                gang_weight=None) -> "SchedulingProfile":
        """Replace one profile's weight row IN PLACE (same name, same
        index — the tensor row a tuner writes). Runs the EXACT ctor
        validation (unknown priorities, duplicate names, policy weight
        bounds) against the full trial set; on failure nothing mutates.
        Returns the installed profile. `weights` is a {priority name:
        weight} mapping (or the ctor's tuple form); empty means the
        DefaultProvider vector. `tensor_mode()` stays dynamic, so an
        identity write of the default vector does NOT flip a degenerate
        default set into tensor mode."""
        if isinstance(name_or_index, int):
            i = name_or_index
            if not 0 <= i < len(self.profiles):
                raise ProfileValidationError(f"no profile at index {i}")
        else:
            idx = self._index.get(name_or_index)
            if idx is None:
                raise ProfileValidationError(
                    f"no profile named {name_or_index!r}")
            i = idx
        old = self.profiles[i]
        if isinstance(weights, dict):
            wt = tuple(sorted((str(k), int(v)) for k, v in weights.items()))
        else:
            wt = tuple(weights)
        cand = SchedulingProfile(
            name=old.name, weights=wt,
            rank_aware=old.rank_aware if rank_aware is None
            else bool(rank_aware),
            gang_weight=old.gang_weight if gang_weight is None
            else int(gang_weight))
        trial = list(self.profiles)
        trial[i] = cand
        # ctor-equivalent validation by construction: the trial set runs
        # the same validate() a fresh ProfileSet would
        ProfileSet(trial, validate=True)
        self.profiles[i] = cand
        self.version += 1
        return cand

    def snapshot(self) -> "ProfileSet":
        """An immutable-enough copy for replay capture: profiles are
        frozen dataclasses, so a fresh list pins the rows as of NOW —
        later set_row() calls replace entries in the LIVE list and leave
        the snapshot's rows untouched (round-18 rule: every cross-run
        decision input is recorded)."""
        snap = ProfileSet(list(self.profiles), validate=False)
        snap.version = self.version
        return snap

    # -- lookups -------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.profiles)

    def __iter__(self):
        return iter(self.profiles)

    @property
    def default(self) -> SchedulingProfile:
        return self.profiles[0]

    def index_of(self, scheduler_name: str) -> Optional[int]:
        """Profile index for a pod's spec.schedulerName, or None when no
        profile claims it (the caller must REPORT, not default-score)."""
        return self._index.get(scheduler_name)

    def profile_for(self, scheduler_name: str) -> Optional[SchedulingProfile]:
        i = self.index_of(scheduler_name)
        return None if i is None else self.profiles[i]

    def gang_weight_for(self, scheduler_name: str) -> int:
        p = self.profile_for(scheduler_name)
        return p.gang_weight if (p is not None and p.rank_aware) else 0

    def tensor_mode(self) -> bool:
        """True when the kernels must run the weight-tensor program: more
        than one profile, any non-default weight vector, or any
        rank-aware profile. False = the pre-profile fast path (exact old
        kernel programs; decisions trivially bit-identical)."""
        from kubernetes_tpu.factory import DEFAULT_PRIORITY_WEIGHTS
        if len(self.profiles) > 1:
            return True
        p = self.profiles[0]
        return p.rank_aware or (
            p.weights and dict(p.weights) != DEFAULT_PRIORITY_WEIGHTS)

    # -- device tensor -------------------------------------------------------
    def kernel_row(self, i: int) -> dict:
        """Kernel-keyed weight dict for profile `i` (gang_locality
        included — 0 unless rank-aware)."""
        from kubernetes_tpu.factory import tpu_kernel_weights
        p = self.profiles[i]
        row = tpu_kernel_weights(p.name_weights())
        if row is None:   # unreachable after validate(); stay safe
            raise ProfileValidationError(
                f"profile {p.name}: priorities not kernel-expressible")
        row["gang_locality"] = p.gang_weight if p.rank_aware else 0
        return row

    def union_kernel_weights(self) -> dict:
        """Static trace-time gate dict: a priority family is compiled in
        iff ANY profile weights it (per-pod rows then scale it, including
        to zero). This is the `weights` argument of every tensor-mode
        kernel call."""
        from kubernetes_tpu.ops.kernels import PRIORITY_AXIS
        union = {k: 0 for k in PRIORITY_AXIS}
        for i in range(len(self.profiles)):
            for k, w in self.kernel_row(i).items():
                union[k] = max(union[k], int(w))
        return union

    def weight_table(self) -> np.ndarray:
        """The [profiles x priorities] scoring tensor, column order =
        ops.kernels.PRIORITY_AXIS. Uploaded once, resident; kernels
        gather row `profile_id` per pod."""
        from kubernetes_tpu.ops.kernels import PRIORITY_AXIS
        tab = np.zeros((len(self.profiles), len(PRIORITY_AXIS)),
                       dtype=np.int64)
        for i in range(len(self.profiles)):
            row = self.kernel_row(i)
            for j, key in enumerate(PRIORITY_AXIS):
                tab[i, j] = int(row.get(key, 0))
        return tab

    # -- oracle side ---------------------------------------------------------
    def oracle_configs(self, i: int, services_fn=lambda: [],
                       replicasets_fn=lambda: [],
                       hard_pod_affinity_weight: int = 1) -> list:
        """Per-profile PriorityConfig list for the serial referee — the
        SAME weight vector the tensor row carries, so per-profile parity
        is pinnable (the gang-locality objective is injected per trial by
        the shell, not here: it needs the trial's live zone counts)."""
        from kubernetes_tpu.factory import build_priority_configs
        return build_priority_configs(
            self.profiles[i].name_weights(), services_fn=services_fn,
            replicasets_fn=replicasets_fn,
            hard_pod_affinity_weight=hard_pod_affinity_weight)

    # -- unknown-profile reporting -------------------------------------------
    def report_unknown(self, pod, recorder=None) -> None:
        """Book a pod no profile claims: counter + (once per uid) a
        FailedScheduling event. NEVER default-scores."""
        self.unknown_names[pod.scheduler_name] = \
            self.unknown_names.get(pod.scheduler_name, 0) + 1
        if pod.uid in self._unknown_seen:
            return
        self._unknown_seen.add(pod.uid)
        if len(self._unknown_seen) > 65536:
            self._unknown_seen.clear()
        PROFILE_UNKNOWN.inc()
        if recorder is not None:
            from kubernetes_tpu.store.record import WARNING
            recorder.pod_event(
                pod, WARNING, "FailedScheduling",
                f"no scheduling profile claims "
                f"schedulerName={pod.scheduler_name!r}")

    def note_scheduled(self, i: int, count: int = 1) -> None:
        PROFILE_SCHEDULED.labels(self.profiles[i].name).inc(count)
        self.scheduled_counts[i] += count

    # -- /debug/sched --------------------------------------------------------
    def debug_state(self) -> dict:
        from kubernetes_tpu.ops.kernels import PRIORITY_AXIS
        tab = self.weight_table()
        return {
            "priority_axis": list(PRIORITY_AXIS),
            "profiles": [{
                "name": p.name,
                "rank_aware": p.rank_aware,
                "weights": tab[i].tolist(),
                "scheduled": self.scheduled_counts[i],
            } for i, p in enumerate(self.profiles)],
            "tensor_mode": self.tensor_mode(),
            "unknown_scheduler_names": dict(self.unknown_names),
        }
