"""Pod-lifecycle ledger — per-pod phase-stamped latency decomposition.

The paper's claim is throughput *with identical decisions*, and the soak
scoreboard (ROADMAP item 5) scores pod-startup SLO percentiles — but until
this round no pod could answer "where did my 5 seconds go?". The ledger is
a low-overhead per-pod phase stamper: monotonic (`time.perf_counter`)
timestamps at each lifecycle boundary,

    admission -> enqueue -> pop -> encode -> dispatch -> fetch
              -> commit -> copyout

stamped by the admission surface (admission — the apiserver/store accept
of the pod create, BEFORE the informer delivers it to queue.add; absent
for pods that never crossed an admission gate, where it collapses onto
enqueue), the queue (enqueue/pop), the TPU burst drivers
(encode/dispatch/fetch — one shared stamp per launch, so a 10k-pod burst
pays O(1) clock reads plus O(pods) dict writes, never a per-pod syscall),
the store's commit verbs (commit — the `commit_wave` landing), and the
commit core's watch copy-out sink (copyout — stamped from inside BOTH
`native/commitcore.cpp` and the `PyCommitCore` twin via the fan-out sink).

Phase durations are differences of consecutive stamps, so they telescope:
the seven phases sum EXACTLY to copyout - admission (the contract test
pins per-pod sums against measured burst wall time; admission collapses
onto enqueue when no gate stamped it). Folds are batched: one vectorized
`observe_batch` per phase per committed wave, not 7 histogram walks per
pod.

A 429-shed pod's record is EVICTED at rejection (`evict`): first-stamp-
wins semantics would otherwise carry the shed attempt's timestamp into
the readmitted pod's record and bill the client's backoff as startup
latency — the readmit must measure from its own accepted create.

Exposed families:
- pod_e2e_duration_seconds{phase} — the decomposition histograms
  (LATENCY_BUCKETS: the µs..100s ladder; queue waits and µs commits share
  one family without crushing either end);
- pod_startup_seconds_p50 / _p99 — callback gauges over a bounded
  reservoir of enqueue->commit latencies (the density.go-style SLO view);
- pod_startup_slo_ok — 1 when p99 <= slo_seconds (default 5s, density.go:56).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from kubernetes_tpu import obs
from kubernetes_tpu.obs.registry import LATENCY_BUCKETS

# stamp slots (indices into a pod's record)
(ADMISSION, ENQUEUE, POP, ENCODE, DISPATCH, FETCH, COMMIT,
 COPYOUT) = range(8)

#: phase names, in stamp order; PHASES[i] = stamps[i+1] - stamps[i]
PHASES = ("admission", "queue", "encode", "dispatch", "fetch", "commit",
          "fanout")

POD_E2E = obs.histogram(
    "pod_e2e_duration_seconds",
    "Per-pod lifecycle phase durations: admission (apiserver/store "
    "accept->informer-delivered enqueue; zero for pods that never "
    "crossed an admission gate), queue (enqueue->pop), encode "
    "(pop->features encoded), dispatch (encode->device program "
    "dispatched), fetch (dispatch->packed block fetched), commit "
    "(fetch->commit_wave landed in the store), fanout (commit->first "
    "watch copy-out, stamped by the commit core).",
    ("phase",), buckets=LATENCY_BUCKETS)

LEDGER_EVICTED = obs.counter(
    "pod_ledger_evicted_total",
    "Pod ledger records evicted before completing (bound on in-flight "
    "records; an eviction means a pod sat pending longer than the "
    "ledger's capacity window).")

LEDGER_FINALIZED = obs.counter(
    "pod_ledger_finalized_total",
    "Pod ledger records finalized at pod DELETION while still holding an "
    "in-flight slot (pending record, or bound and awaiting the copy-out "
    "stamp): the completion reaper and PodGC delete pods whose bind "
    "events no watcher may ever copy out — without this hook those "
    "records would be retained until the capacity bound evicts them.")

#: density.go:56 — the pod-startup latency SLO the gauges score against
STARTUP_SLO_SECONDS = 5.0

#: rolling window (seconds) the windowed SLO twins score over — long
#: enough to smooth a single launch, short enough that a minute-40
#: degradation flips the gauge within a window
STARTUP_WINDOW_SECONDS = 30.0

#: SLO error budget: the fraction of pods allowed to miss the startup
#: SLO before the burn rate reads 1.0 (burn = violation_frac / budget)
STARTUP_ERROR_BUDGET = 0.01


class PodLifecycleLedger:
    """Process-global per-pod phase stamper (see module docstring)."""

    def __init__(self, capacity: int = 1 << 17,
                 reservoir: int = 1 << 16):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._recs: dict[str, list] = {}      # key -> [t0..t6] (pre-commit)
        self._awaiting: dict[str, float] = {}  # key -> commit ts (fan-out)
        self._e2e: deque = deque(maxlen=reservoir)   # admission->commit
        # (commit_ts, latency) pairs for the WINDOWED twins: the
        # cumulative reservoir above is since-reset and averages a
        # late-run stall away; this one is filtered by commit time
        self._recent: deque = deque(maxlen=reservoir)
        #: windowed-reservoir retention (round 23): commit_many trims
        #: `_recent` entries older than this at APPEND time — 4x the
        #: default startup window so every in-repo readout (30 s
        #: windowed twins, the tuner's 60 s lane windows) stays whole
        #: while minutes-scale soaks hold O(window) memory
        self.retention_seconds = 4 * STARTUP_WINDOW_SECONDS
        self._phase_sum = {p: 0.0 for p in PHASES}
        self._completed = 0
        self._trace: Optional[dict] = None    # key -> stamps (test mode)

    # -- configuration -------------------------------------------------------
    def set_trace(self, on: bool) -> None:
        """Keep completed records' raw stamps (contract-test mode)."""
        with self._lock:
            self._trace = {} if on else None

    def reset(self) -> None:
        """Drop every record and accumulated stat (bench run isolation)."""
        with self._lock:
            self._recs.clear()
            self._awaiting.clear()
            self._e2e.clear()
            self._recent.clear()
            self._phase_sum = {p: 0.0 for p in PHASES}
            self._completed = 0
            if self._trace is not None:
                self._trace = {}

    # -- stamping ------------------------------------------------------------
    def _open_rec(self, key: str, slot: int, t: Optional[float]) -> None:
        """First stamp wins per slot: a re-queued (backoff) pod keeps its
        original arrival, so queue time honestly includes backoff waits —
        and an admission-stamped pod's later enqueue fills ENQUEUE without
        disturbing the accepted-create stamp."""
        with self._lock:
            rec = self._recs.get(key)
            if rec is None:
                if len(self._recs) >= self._capacity:
                    # bound in-flight records: evict the oldest insertion
                    self._recs.pop(next(iter(self._recs)))
                    LEDGER_EVICTED.inc()
                rec = self._recs[key] = [None] * 8
            if rec[slot] is None:
                rec[slot] = t if t is not None else time.perf_counter()

    def stamp_admission(self, key: str, t: Optional[float] = None) -> None:
        """Apiserver/store accept of the pod create — stamped BEFORE the
        informer delivers the pod to queue.add, so the admission phase
        measures watch-to-enqueue time. First accept wins."""
        self._open_rec(key, ADMISSION, t)

    def stamp_enqueue(self, key: str, t: Optional[float] = None) -> None:
        """First enqueue wins (see _open_rec)."""
        self._open_rec(key, ENQUEUE, t)

    def _open_many(self, keys, slot: int, t: Optional[float]) -> None:
        """Batched _open_rec: one lock + one shared timestamp for a whole
        accepted-create / enqueue batch (first stamp still wins per
        slot)."""
        tt = t if t is not None else time.perf_counter()
        with self._lock:
            recs = self._recs
            for key in keys:
                rec = recs.get(key)
                if rec is None:
                    if len(recs) >= self._capacity:
                        recs.pop(next(iter(recs)))
                        LEDGER_EVICTED.inc()
                    rec = recs[key] = [None] * 8
                if rec[slot] is None:
                    rec[slot] = tt

    def stamp_admission_many(self, keys,
                             t: Optional[float] = None) -> None:
        """One batched admission stamp per accepted create_many flush —
        the serving ingest path's one-ledger-call-per-batch contract."""
        self._open_many(keys, ADMISSION, t)

    def stamp_enqueue_many(self, keys, t: Optional[float] = None) -> None:
        """One batched enqueue stamp per queue.add_many batch."""
        self._open_many(keys, ENQUEUE, t)

    def evict(self, key: str) -> None:
        """Admission rejected the pod (429 shed): drop its in-flight
        record outright. First-stamp-wins would otherwise let a
        shed-then-readmitted pod keep the SHED attempt's stamps and bill
        the client's backoff as startup latency — the readmit opens a
        fresh record at its own accepted create."""
        with self._lock:
            self._recs.pop(key, None)

    def evict_many(self, keys) -> None:
        """Batched evict — one lock for a whole shed batch (the gated
        create_many path's 429 tail)."""
        with self._lock:
            recs = self._recs
            for key in keys:
                recs.pop(key, None)

    def finalize_delete(self, key: str) -> None:
        """The pod was DELETED from the store: drop whatever in-flight
        slot it still holds — a pending record (arrived, never bound) or
        the awaiting-copy-out commit stamp (bound, but its bind event was
        never copied out by a watcher and now never will be). Without
        this hook a completion reaper or PodGC deleting bound pods leaks
        one awaiting entry per deletion until the capacity bound evicts
        them — the round-17 leak fix; the soak-shaped unit test pins the
        steady-state map sizes."""
        with self._lock:
            dropped = self._recs.pop(key, None) is not None
            dropped = (self._awaiting.pop(key, None) is not None) or dropped
        if dropped:
            LEDGER_FINALIZED.inc()

    def stamp(self, key: str, slot: int, t: Optional[float] = None) -> None:
        with self._lock:
            rec = self._recs.get(key)
            if rec is not None:
                rec[slot] = t if t is not None else time.perf_counter()

    def stamp_many(self, keys, slot: int,
                   t: Optional[float] = None) -> None:
        """One shared timestamp for a whole wave/burst boundary — O(1)
        clock reads, O(pods) dict writes."""
        tt = t if t is not None else time.perf_counter()
        with self._lock:
            recs = self._recs
            for k in keys:
                rec = recs.get(k)
                if rec is not None:
                    rec[slot] = tt

    def stamp_serial(self, key: str, t: Optional[float] = None) -> None:
        """Serial-cycle boundary: the host twin has no separate device
        dispatch/fetch, so encode/dispatch/fetch land on one stamp and the
        telescoping identity holds on every path."""
        tt = t if t is not None else time.perf_counter()
        with self._lock:
            rec = self._recs.get(key)
            if rec is not None:
                rec[ENCODE] = rec[DISPATCH] = rec[FETCH] = tt

    # -- completion ----------------------------------------------------------
    def commit_many(self, keys, t: Optional[float] = None) -> None:
        """A wave of bindings landed (`Store.commit_wave` / bind verbs):
        fold each pod's pre-commit phases into the histograms in one
        vectorized batch per phase, record the admission->commit latency
        in the startup reservoir (= enqueue->commit for pods no admission
        gate stamped), and park the commit stamp for the fan-out phase
        (completed by the commit core's copy-out sink)."""
        tt = t if t is not None else time.perf_counter()
        folds: list[list] = []
        fold_keys: list[str] = []
        with self._lock:
            recs = self._recs
            for k in keys:
                rec = recs.pop(k, None)
                if rec is None:
                    continue
                fold_keys.append(k)
                rec[COMMIT] = tt
                # a pod that never crossed an admission gate collapses the
                # admission phase to zero width at its enqueue stamp
                if rec[ADMISSION] is None:
                    rec[ADMISSION] = rec[ENQUEUE]
                # missing intermediate stamps (a path that skipped a
                # boundary) inherit the previous stamp: the phase reads 0
                # and the telescoping identity survives
                for i in range(ENQUEUE, COMMIT + 1):
                    if rec[i] is None:
                        rec[i] = rec[i - 1]
                folds.append(rec)
                self._awaiting[k] = tt
                if len(self._awaiting) > self._capacity:
                    self._awaiting.pop(next(iter(self._awaiting)))
                if self._trace is not None:
                    self._trace[k] = rec
            if not folds:
                return
            for k, rec in zip(fold_keys, folds):
                lat = rec[COMMIT] - rec[ADMISSION]
                self._e2e.append(lat)
                # the key rides along so windowed readouts can filter by
                # lane (round 22: the tuner's shadow-vs-incumbent split)
                self._recent.append((tt, lat, k))
            # age-out at append time (round 23): entries older than every
            # readout window can never be walked again (_recent is
            # commit-time ordered), so a minutes-scale soak holds
            # O(window) memory instead of riding the reservoir cap. The
            # retention carries slack past the default 30 s window because
            # the tuner's lane readouts ask for 60 s; the cutoff keys off
            # this batch's stamp, so synthetic clocks trim exactly like
            # wall time.
            cutoff = tt - self.retention_seconds
            recent = self._recent
            while recent and recent[0][0] < cutoff:
                recent.popleft()
            self._completed += len(folds)
        # histogram folds outside the ledger lock (families self-lock)
        for slot, phase in ((ENQUEUE, "admission"), (POP, "queue"),
                            (ENCODE, "encode"), (DISPATCH, "dispatch"),
                            (FETCH, "fetch"), (COMMIT, "commit")):
            vals = [max(0.0, r[slot] - r[slot - 1]) for r in folds]
            POD_E2E.labels(phase).observe_batch(vals)
            self._phase_sum[PHASES[slot - 1]] += sum(vals)

    def has_awaiting(self) -> bool:
        return bool(self._awaiting)

    def copyout(self, key: str, t: Optional[float] = None) -> None:
        """First watch copy-out of the pod's bind event (stamped via the
        commit core's fan-out sink — both native and twin)."""
        with self._lock:
            committed = self._awaiting.pop(key, None)
            if committed is None:
                return
            tt = t if t is not None else time.perf_counter()
            d = max(0.0, tt - committed)
            self._phase_sum["fanout"] += d
            if self._trace is not None and key in self._trace:
                self._trace[key][COPYOUT] = tt
        POD_E2E.labels("fanout").observe(d)

    # -- readout -------------------------------------------------------------
    def trace_record(self, key: str) -> Optional[list]:
        with self._lock:
            return None if self._trace is None else self._trace.get(key)

    def percentile(self, q: float) -> float:
        """Startup (admission->commit; enqueue->commit when no admission
        gate stamped the pod) latency percentile over the bounded
        reservoir; 0.0 with no data."""
        with self._lock:
            vals = sorted(self._e2e)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    def slo_ok(self, slo: float = STARTUP_SLO_SECONDS) -> float:
        p99 = self.percentile(0.99)
        return 1.0 if p99 <= slo else 0.0

    # -- windowed twins ------------------------------------------------------
    def _window_vals(self, window: Optional[float],
                     now: Optional[float], match=None) -> list:
        """Startup latencies of pods committed within the trailing
        window (commit-stamp clock: perf_counter). `match` filters by
        pod key — the per-lane readout (tuner shadow vs incumbent)."""
        w = STARTUP_WINDOW_SECONDS if window is None else window
        tt = time.perf_counter() if now is None else now
        cutoff = tt - w
        with self._lock:
            # _recent is commit-time ordered: walk from the newest end
            out = []
            for t, lat, key in reversed(self._recent):
                if t < cutoff:
                    break
                if match is None or match(key):
                    out.append(lat)
        return out

    def window_percentile(self, q: float, window: Optional[float] = None,
                          now: Optional[float] = None,
                          match=None) -> float:
        """Startup percentile over pods committed in the trailing window
        only — the rolling twin of `percentile` (which is since-reset
        and shows a late-run stall only after it has drowned the early
        samples). 0.0 with no pods in the window. `match` (key ->
        bool) restricts to one lane's pods."""
        vals = sorted(self._window_vals(window, now, match))
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    def window_count(self, window: Optional[float] = None,
                     now: Optional[float] = None, match=None) -> int:
        """Pods committed in the trailing window (optionally one lane's)
        — the promotion gate's minimum-evidence denominator."""
        return len(self._window_vals(window, now, match))

    def window_violation_fraction(self, slo: float = STARTUP_SLO_SECONDS,
                                  window: Optional[float] = None,
                                  now: Optional[float] = None) -> float:
        """Fraction of pods committed in the trailing window whose
        startup latency missed the SLO; 0.0 with no pods."""
        vals = self._window_vals(window, now)
        if not vals:
            return 0.0
        return sum(1 for v in vals if v > slo) / len(vals)

    def window_slo_ok(self, slo: float = STARTUP_SLO_SECONDS,
                      window: Optional[float] = None,
                      now: Optional[float] = None) -> float:
        p99 = self.window_percentile(0.99, window=window, now=now)
        return 1.0 if p99 <= slo else 0.0

    def burn_rate(self, slo: float = STARTUP_SLO_SECONDS,
                  budget: float = STARTUP_ERROR_BUDGET,
                  window: Optional[float] = None,
                  now: Optional[float] = None) -> float:
        """SLO burn rate over the trailing window: the violation
        fraction divided by the error budget (1.0 = burning budget
        exactly as provisioned; >1 = on track to exhaust it)."""
        return self.window_violation_fraction(slo, window, now) / budget

    def snapshot(self) -> dict:
        """Bench/harness readout: startup percentiles + the per-phase
        split over everything folded since the last reset(). phase_split
        values are POD-SECONDS (the sum over pods of that phase's
        duration) — burst-shared boundaries mean each pod's phase spans
        the launch's wall time, so the split reads as relative weight,
        not as wall seconds."""
        with self._lock:
            split = dict(self._phase_sum)
            n = self._completed
        return {
            "startup_p50": round(self.percentile(0.50), 6),
            "startup_p99": round(self.percentile(0.99), 6),
            "startup_slo_ok": bool(self.slo_ok()),
            "startup_p50_windowed": round(self.window_percentile(0.50), 6),
            "startup_p99_windowed": round(self.window_percentile(0.99), 6),
            "startup_slo_ok_windowed": bool(self.window_slo_ok()),
            "slo_burn_rate": round(self.burn_rate(), 6),
            "phase_split": {p: round(v, 6) for p, v in split.items()},
            "pods_completed": n,
        }

    def debug_state(self) -> dict:
        with self._lock:
            return {"in_flight": len(self._recs),
                    "awaiting_fanout": len(self._awaiting),
                    "completed": self._completed}


#: the process-global ledger every layer stamps into
LEDGER = PodLifecycleLedger()

# first-class SLO gauges: read the ledger at collect time (GaugeFunc)
_P50 = obs.gauge("pod_startup_seconds_p50",
                 "Median pod startup (enqueue->commit) latency over the "
                 "ledger reservoir.")
_P50.set_function(lambda: LEDGER.percentile(0.50))
_P99 = obs.gauge("pod_startup_seconds_p99",
                 "p99 pod startup (enqueue->commit) latency over the "
                 "ledger reservoir.")
_P99.set_function(lambda: LEDGER.percentile(0.99))
_SLO = obs.gauge("pod_startup_slo_ok",
                 "1 when the p99 pod-startup latency meets the 5s SLO "
                 "(density.go:56); vacuously 1 with no data.")
_SLO.set_function(lambda: LEDGER.slo_ok())

# windowed twins: the rolling-window view the soak scoreboard samples —
# a late-run stall flips these while the cumulative gauges above are
# still averaging it away (pinned by the stall test)
_P50W = obs.gauge("pod_startup_seconds_p50_windowed",
                  "Median pod startup latency over pods committed in the "
                  "trailing 30s window (rolling twin of "
                  "pod_startup_seconds_p50; 0 with no pods in window).")
_P50W.set_function(lambda: LEDGER.window_percentile(0.50))
_P99W = obs.gauge("pod_startup_seconds_p99_windowed",
                  "p99 pod startup latency over pods committed in the "
                  "trailing 30s window (rolling twin of "
                  "pod_startup_seconds_p99; 0 with no pods in window).")
_P99W.set_function(lambda: LEDGER.window_percentile(0.99))
_SLOW = obs.gauge("pod_startup_slo_ok_windowed",
                  "1 when the trailing-window p99 startup latency meets "
                  "the 5s SLO; vacuously 1 with no pods in window.")
_SLOW.set_function(lambda: LEDGER.window_slo_ok())
_BURN = obs.gauge("slo_burn_rate",
                  "Startup-SLO burn rate over the trailing window: "
                  "fraction of pods missing the 5s SLO divided by the 1% "
                  "error budget (1.0 = burning exactly as provisioned).")
_BURN.set_function(lambda: LEDGER.burn_rate())
