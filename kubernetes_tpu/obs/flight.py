"""Burst flight recorder — a bounded ring of the last N fused bursts.

A parity-fuzz failure used to leave nothing to replay: one-in-42-seed
catches died with an assert diff and no artifact. The recorder keeps, for
each single-launch burst (uniform K-batch, generic scan, fused segmented
window, pressure wave), the inputs that determine the decision — pod set,
walk state (last_index / last_node_index), rotation cursor, NodeTree
epoch, device-matrix epoch, victim-table shape — plus the packed fetch
block and the commit outcome. `dump()` turns the ring into an attachable
JSON artifact; `replay()` re-runs a recorded burst through the pure-Python
oracle (the serial referee) and asserts bit-identity, turning a fuzz catch
into a reproducible unit.

Two capture levels (module-global `RECORDER`):
- "digest" (default, always on): O(1) refs + one ndarray copy per burst —
  cheap enough for the headline bench (no device traffic, no clones).
- "replay": additionally clones the node snapshot, the NodeTree cursor
  state, and the service/replicaset lists, so `replay()` can re-derive the
  burst's decisions from scratch. Opt-in (the shell fuzzes turn it on;
  KTPU_FLIGHT=replay forces it) because the clone is O(cluster) per burst.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

try:
    import numpy as np
except ImportError:          # pragma: no cover — minimal interpreters
    # `python -S` consumers (the native ASan stress subprocess) import the
    # store, which transitively imports this module; they never RECORD
    # bursts, so the recorder degrades to inert instead of killing the
    # import chain
    np = None


class BurstRecord:
    __slots__ = ("kind", "segments", "names", "li", "lni", "zone_index",
                 "tree_epoch", "dev_epoch", "vic", "blocks", "outcome",
                 "capture", "notes")

    def __init__(self, kind: str, segments, names, li: int, lni: int,
                 zone_index, tree_epoch, dev_epoch: int, vic,
                 capture: Optional[dict]):
        self.kind = kind              # uniform | scan | fused | pressure
        self.segments = segments      # [(pods, is_gang), ...] (refs)
        self.names = names            # the burst's first enumeration (ref)
        self.li = li                  # last_index before the launch
        self.lni = lni                # last_node_index before the launch
        self.zone_index = zone_index  # rotation cursor before the launch
        self.tree_epoch = tree_epoch  # NodeTree membership epoch
        self.dev_epoch = dev_epoch    # device-matrix upload/scatter epoch
        self.vic = vic                # victim-table digest (shape/rows)
        self.blocks: list = []        # packed fetch block copies
        self.outcome: Optional[dict] = None
        self.capture = capture        # deep replay inputs (replay mode)
        self.notes: list[str] = []

    @property
    def pods(self) -> list:
        return [p for seg, _g in self.segments for p in seg]


class FlightRecorder:
    def __init__(self, capacity: int = 8):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self.mode = os.environ.get("KTPU_FLIGHT", "digest")

    # -- configuration -------------------------------------------------------
    def configure(self, mode: Optional[str] = None,
                  capacity: Optional[int] = None) -> None:
        with self._lock:
            if mode is not None:
                if mode not in ("off", "digest", "replay"):
                    raise ValueError(f"unknown flight mode {mode!r}")
                self.mode = mode
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=max(int(capacity), 1))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    # -- capture (called by the TPU burst drivers) ---------------------------
    def begin(self, kind: str, algo, segments, names,
              node_infos) -> Optional[BurstRecord]:
        """Open a record for one burst launch. Must run BEFORE the first
        wave commit can mutate the cache's NodeInfos (the deep clone has to
        see the pre-burst world)."""
        if self.mode == "off":
            return None
        tree = getattr(algo, "node_tree", None)
        vt = getattr(getattr(algo, "encoder", None), "_vt", None)
        vic = None if vt is None else {
            "P": int(vt.P), "rows": int(vt.valid.shape[0]),
            "dirty_rows": (None if vt.dirty_rows is None
                           else len(vt.dirty_rows))}
        capture = None
        if self.mode == "replay":
            capture = {
                "infos": {k: ni.clone() for k, ni in node_infos.items()},
                "tree": self._tree_snapshot(tree),
                "services": list(algo.services_fn()),
                "replicasets": list(algo.replicasets_fn()),
                "pct": algo.percentage_of_nodes_to_score,
                "hpaw": algo.hard_pod_affinity_weight,
                "enabled": (None if algo.enabled_predicates is None
                            else set(algo.enabled_predicates)),
                "weights": algo.priority_name_weights,
            }
            # round-19 scheduling profiles: the set is decision INPUT
            # (per-pod weight rows + the rank-aware gang objective), so
            # replay must select configs per pod the same way. Round 22
            # makes rows WRITABLE (the tuner), so the capture pins a
            # SNAPSHOT + the active weight-table slice — a mid-run
            # set_row() must not retro-edit an already-recorded burst
            # (round-18 rule: every cross-run input is RECORDED).
            profs = getattr(algo, "profiles", None)
            if profs is not None:
                capture["profiles"] = profs.snapshot()
                capture["wtab"] = profs.weight_table().copy()
                capture["profile_version"] = profs.version
            else:
                capture["profiles"] = None
        rec = BurstRecord(
            kind, [(list(seg), bool(g)) for seg, g in segments],
            list(names), algo.last_index, algo.last_node_index,
            None if tree is None else tree.zone_index,
            None if tree is None else getattr(tree, "epoch", None),
            getattr(algo, "_dev_epoch", 0), vic, capture)
        with self._lock:
            self._ring.append(rec)
        return rec

    @staticmethod
    def note_block(rec: Optional[BurstRecord], block) -> None:
        """Attach (a copy of) one packed fetch block to the record."""
        if rec is not None:
            rec.blocks.append(np.asarray(block).copy())

    @staticmethod
    def note_outcome(rec: Optional[BurstRecord], outcome: dict) -> None:
        if rec is not None:
            rec.outcome = outcome

    def note_crash(self, tag: str) -> None:
        """Annotate the most recent record (the commit crash-seam hook:
        the burst whose commit died is the one worth dumping)."""
        with self._lock:
            if self._ring:
                self._ring[-1].notes.append(tag)

    @staticmethod
    def _tree_snapshot(tree) -> Optional[dict]:
        if tree is None:
            return None
        return {"tree": {z: list(ns) for z, ns in tree._tree.items()},
                "zones": list(tree._zones),
                "chk": tree.checkpoint()}

    @staticmethod
    def _rebuild_tree(snap: Optional[dict]):
        if snap is None:
            return None
        from kubernetes_tpu.cache.node_tree import NodeTree
        t = NodeTree()
        t._tree = {z: list(ns) for z, ns in snap["tree"].items()}
        t._zones = list(snap["zones"])
        t.num_nodes = sum(len(ns) for ns in t._tree.values())
        t._last_index = {z: 0 for z in t._zones}
        # adopt the recorded epoch so restore() replays the cursors
        # exactly (an epoch mismatch means membership churned under the
        # checkpoint and restore re-grounds instead)
        t.epoch = snap["chk"][3]
        t.restore(snap["chk"])
        return t

    # -- artifacts -----------------------------------------------------------
    def describe(self) -> dict:
        """JSON-able view of the ring (newest last)."""
        out = []
        for rec in self.records():
            out.append({
                "kind": rec.kind,
                "segments": [{"pods": [p.key for p in seg],
                              "gang": g} for seg, g in rec.segments],
                "classes": sorted({p.labels.get("app", "")
                                   for p in rec.pods}),
                "last_index": rec.li,
                "last_node_index": rec.lni,
                "zone_index": rec.zone_index,
                "node_tree_epoch": rec.tree_epoch,
                "dev_epoch": rec.dev_epoch,
                "victim_table": rec.vic,
                "n_nodes": len(rec.names),
                "blocks": [b.tolist() for b in rec.blocks],
                "outcome": rec.outcome,
                "replayable": rec.capture is not None
                and rec.kind in ("uniform", "scan", "fused"),
                "notes": list(rec.notes),
            })
        return {"flight_records": out}

    def dump(self, path: Optional[str] = None):
        """Write the ring as a JSON artifact; returns the path (or the
        document when no path is given)."""
        doc = self.describe()
        if path is None:
            return doc
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return path

    # -- replay (the oracle referee) -----------------------------------------
    def replay(self, rec: BurstRecord) -> list[str]:
        """Re-run a recorded burst through the pure-Python oracle and
        compare decision-for-decision with the recorded outcome. Returns a
        list of mismatch descriptions (empty = bit-identical). Requires a
        replay-mode record; pressure records are dump-only."""
        if rec.capture is None:
            raise ValueError("record has no replay capture "
                             "(RECORDER.configure(mode='replay') first)")
        if rec.kind not in ("uniform", "scan", "fused"):
            raise ValueError(f"{rec.kind} records are dump-only")
        from kubernetes_tpu.factory import (build_predicate_set,
                                            build_priority_configs,
                                            DEFAULT_PREDICATE_NAMES)
        from kubernetes_tpu.oracle.generic_scheduler import (
            FitError, GenericScheduler, default_priority_configs)
        cap = rec.capture
        infos = {k: ni.clone() for k, ni in cap["infos"].items()}
        tree = self._rebuild_tree(cap["tree"])
        services = cap["services"]
        replicasets = cap["replicasets"]
        hpaw = cap["hpaw"]
        oracle = GenericScheduler(
            percentage_of_nodes_to_score=cap["pct"],
            hard_pod_affinity_weight=hpaw,
            nominated_pods_fn=lambda _n: [])
        oracle.last_index, oracle.last_node_index = rec.li, rec.lni
        profiles = cap.get("profiles")
        if profiles is not None and cap.get("wtab") is not None:
            # the recorded tensor slice must still derive from the
            # snapshot — a divergence means the capture failed to pin the
            # rows across a tuner set_row() (replay would silently score
            # with the WRONG weights otherwise)
            if not np.array_equal(profiles.weight_table(), cap["wtab"]):
                return ["recorded weight table diverges from the profile "
                        "snapshot (capture did not pin the tensor rows)"]
        if profiles is not None:
            prof_cfgs = [profiles.oracle_configs(
                i, services_fn=lambda: services,
                replicasets_fn=lambda: replicasets,
                hard_pod_affinity_weight=hpaw)
                for i in range(len(profiles))]

            def cfgs_for(pod):
                pid = profiles.index_of(pod.scheduler_name)
                return prof_cfgs[0 if pid is None else pid]
        elif cap["weights"] is not None:
            cfgs = build_priority_configs(
                cap["weights"], services_fn=lambda: services,
                replicasets_fn=lambda: replicasets,
                hard_pod_affinity_weight=hpaw)
            cfgs_for = lambda _pod: cfgs
        else:
            cfgs = default_priority_configs(
                services_fn=lambda: services,
                replicasets_fn=lambda: replicasets,
                hard_pod_affinity_weight=hpaw)
            cfgs_for = lambda _pod: cfgs
        pred_names = (sorted(cap["enabled"]) if cap["enabled"]
                      else DEFAULT_PREDICATE_NAMES)
        t_consumed = 0   # enumerations consumed (the kernel's carried t)

        def take_names() -> list[str]:
            nonlocal t_consumed
            if t_consumed == 0:
                ns = list(rec.names)
            elif tree is not None:
                ns = tree.list_names()
            else:
                ns = list(rec.names)
            t_consumed += 1
            return ns

        def run_pod(pod, gang_zones=None) -> Optional[str]:
            funcs = build_predicate_set(
                pred_names, infos, services_fn=lambda: services)
            pod_cfgs = cfgs_for(pod)
            gw = (profiles.gang_weight_for(pod.scheduler_name)
                  if profiles is not None and gang_zones is not None else 0)
            if gw:
                # rank-aware gang set-scoring: the replay's twin of the
                # kernel's per-segment zone-count carry
                from kubernetes_tpu.oracle import priorities as prios
                from kubernetes_tpu.oracle.generic_scheduler import (
                    PriorityConfig)
                pod_cfgs = list(pod_cfgs) + [PriorityConfig(
                    "GangLocalityPriority", gw,
                    function=lambda _p, nis, nodes: [
                        prios.gang_locality_map(gang_zones, nis[n.name])
                        for n in nodes])]
            try:
                r = oracle.schedule(pod, infos, take_names(),
                                    predicate_funcs=funcs,
                                    priority_configs=pod_cfgs)
            except FitError:
                return None
            host = r.suggested_host
            assumed = pod.clone()
            assumed.node_name = host
            ni = infos[host].clone()
            ni.add_pod(assumed)
            infos[host] = ni
            if gang_zones is not None:
                from kubernetes_tpu.api.types import get_zone_key
                node = infos[host].node
                z = get_zone_key(node) if node is not None else ""
                if z:
                    gang_zones[z] = gang_zones.get(z, 0) + 1
            return host

        # normalize: uniform/scan records are one non-gang segment
        if rec.kind == "fused":
            expects = rec.outcome["segments"] if rec.outcome else []
        else:
            out = rec.outcome or {}
            expects = [{"status": "failed" if out.get("failed")
                        else "decided", "hosts": out.get("hosts", [])}]
        mism: list[str] = []
        stop = False
        for (seg_pods, is_gang), expect in zip(rec.segments, expects):
            if stop or expect.get("status") == "undecided":
                break
            if is_gang:
                chk = (dict(infos), oracle.last_index,
                       oracle.last_node_index, t_consumed,
                       None if tree is None else tree.checkpoint())
                hosts: list = []
                fail_at = None
                gang_zones: dict = {}   # per-segment zone-count tracker
                for i, p in enumerate(seg_pods):
                    h = run_pod(p, gang_zones=gang_zones)
                    if h is None:
                        fail_at = i
                        break   # the kernel skips the rest of the segment
                    hosts.append(h)
                if fail_at is not None:
                    infos = chk[0]
                    oracle.last_index, oracle.last_node_index = chk[1], chk[2]
                    t_consumed = chk[3]
                    if tree is not None:
                        tree.restore(chk[4])
                    if expect["status"] != "rejected":
                        mism.append(
                            f"gang: oracle rejects at member {fail_at}, "
                            f"device says {expect['status']}")
                    elif expect.get("placed") != fail_at:
                        mism.append(
                            f"gang placed count: oracle {fail_at}, "
                            f"device {expect.get('placed')}")
                else:
                    if expect["status"] != "decided":
                        mism.append(
                            f"gang: oracle places all {len(seg_pods)}, "
                            f"device says {expect['status']}")
                    elif hosts != expect.get("hosts"):
                        mism.append(
                            f"gang hosts diverge: oracle {hosts} != "
                            f"device {expect.get('hosts')}")
                continue
            # singleton run: compare the device-decided prefix; on a
            # recorded failure, the next pod must fail here too and
            # everything after is undecided
            want = list(expect.get("hosts", []))
            for i, p in enumerate(seg_pods):
                if i < len(want):
                    h = run_pod(p)
                    if h != want[i]:
                        mism.append(
                            f"pod {p.key}: oracle {h} != device {want[i]}")
                        stop = True
                        break
                elif expect["status"] == "failed" and i == len(want):
                    h = run_pod(p)
                    if h is not None:
                        mism.append(
                            f"pod {p.key}: oracle places on {h}, device "
                            f"found no node")
                    stop = True
                    break
                else:
                    stop = True   # undecided tail (commit abort)
                    break
        return mism

    def replay_all(self) -> list[str]:
        """Replay every replayable record in the ring; returns the
        accumulated mismatches (empty = every recorded burst re-derives
        bit-identically through the oracle)."""
        errs: list[str] = []
        for i, rec in enumerate(self.records()):
            if rec.capture is None or rec.kind not in ("uniform", "scan",
                                                       "fused"):
                continue
            try:
                for m in self.replay(rec):
                    errs.append(f"record {i} [{rec.kind}]: {m}")
            except Exception as e:   # replay harness bug ≠ silent pass
                errs.append(f"record {i} [{rec.kind}]: replay error: {e!r}")
        return errs


#: the process-global recorder the burst drivers feed
RECORDER = FlightRecorder()


def dump(path: Optional[str] = None):
    """Module-level convenience: `obs.flight.dump()`."""
    return RECORDER.dump(path)
