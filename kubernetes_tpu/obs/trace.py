"""Span tracing — context-propagated spans in a bounded ring buffer,
exportable as Chrome trace-event JSON (load in Perfetto / chrome://tracing).

Promotes utils/tracing.Trace from a log-only step timer to a real tracing
layer: `with span("burst.encode"): ...` records a complete ("X") event;
nesting is carried through a contextvar so child spans know their parent
even across the scheduler's bind threads. The buffer is a deque with a
fixed capacity — tracing is always on, costs one append per span, and old
spans fall off the back instead of growing memory.

Device-cost accounting (the point of the exercise, per CLAUDE.md):
`jax.block_until_ready` does NOT block on the tunneled chip, so device
time is attributed by FETCH timing — the TPU pipeline records
cat="device" spans around the packed-array readback (`np.asarray` /
`jax.device_get`) and cat="host" spans around encode, so host encode vs
device dispatch+readback separate cleanly in the trace viewer.

Consumers: `GET /debug/traces` on the apiserver, `bench.py --trace out.json`.
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

DEFAULT_CAPACITY = 65536

# perf_counter anchor: Chrome wants microsecond timestamps on one clock
_ORIGIN = time.perf_counter()

_buf: deque = deque(maxlen=DEFAULT_CAPACITY)
_current: contextvars.ContextVar = contextvars.ContextVar(
    "obs_span", default=None)
_lock = threading.Lock()


def set_capacity(n: int) -> None:
    """Resize the ring (drops recorded spans)."""
    global _buf
    with _lock:
        _buf = deque(maxlen=max(int(n), 1))


def clear() -> None:
    _buf.clear()


def now() -> float:
    return time.perf_counter()


def _note_dropped(n: int = 1) -> None:
    """Book spans the ring overflowed away (the deque drops them silently;
    this is the observable tripwire). Lazy import: obs/__init__ imports
    this module, so the counter can only be fetched after init — drops are
    rare, and the registry's get-or-create makes the repeat lookup cheap."""
    try:
        from kubernetes_tpu import obs
        obs.counter(
            "obs_trace_dropped_total",
            "Spans dropped from the trace ring buffer on overflow (the "
            "ring keeps the newest spans; resize with "
            "obs.trace.set_capacity).").inc(n)
    except Exception:
        pass   # never let observability bookkeeping break a hot path


def add_span(name: str, t0: float, t1: float, cat: str = "host",
             args: Optional[dict] = None) -> None:
    """Record one complete span from explicit perf_counter timestamps —
    the hot-path API (no context manager overhead). `args` values must be
    JSON-serializable."""
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": (t0 - _ORIGIN) * 1e6, "dur": (t1 - t0) * 1e6,
          "pid": os.getpid(), "tid": threading.get_ident()}
    parent = _current.get()
    if args or parent:
        a = dict(args) if args else {}
        if parent:
            a.setdefault("parent", parent)
        ev["args"] = a
    buf = _buf
    if buf.maxlen is not None and len(buf) >= buf.maxlen:
        _note_dropped()
    buf.append(ev)


@contextmanager
def span(name: str, cat: str = "host", **args):
    """Context-manager span; nests via a contextvar so children record
    their parent chain (propagates across threads started with
    contextvars-aware APIs; explicit `parent=` beats inference)."""
    t0 = time.perf_counter()
    token = _current.set(name)
    try:
        yield
    finally:
        _current.reset(token)
        add_span(name, t0, time.perf_counter(), cat=cat,
                 args=args or None)


def events(limit: Optional[int] = None,
           cat: Optional[str] = None) -> list[dict]:
    """Snapshot of the recorded spans, oldest first. `cat` filters by span
    category (e.g. "device" vs "host"); `limit` keeps only the NEWEST N
    spans after filtering — the /debug/traces query knobs."""
    evs = list(_buf)
    if cat is not None:
        evs = [e for e in evs if e.get("cat") == cat]
    if limit is not None and limit >= 0:
        evs = evs[-limit:] if limit else []
    return evs


def to_chrome(limit: Optional[int] = None,
              cat: Optional[str] = None) -> dict:
    """Chrome trace-event JSON object — Perfetto and chrome://tracing both
    load it directly."""
    return {"traceEvents": events(limit=limit, cat=cat),
            "displayTimeUnit": "ms"}


def export(path: str) -> int:
    """Write the Chrome trace JSON to `path`; returns the span count."""
    evs = to_chrome()
    with open(path, "w") as f:
        json.dump(evs, f)
    return len(evs["traceEvents"])
