"""In-process metrics time-series — the soak scoreboard's sensor plane.

The registry (obs.registry) is cumulative counters, point-in-time
gauges, and cumulative histograms: perfect for "how much since process
start", blind to "when did it degrade". An hour-long soak that falls
over in minute 40 renders the same final /metrics scrape as one that
was slow from the first window. This module closes that gap without an
external Prometheus:

- `TimeSeriesScraper` samples the WHOLE registry on a cadence into a
  bounded columnar ring (newest `capacity` samples win): counters are
  stored as per-sample deltas (rates derive from the sampled dt),
  gauges raw, histograms as per-window bucket deltas reduced to
  windowed p50/p99 at sample time via the same searchsorted shape the
  registry's `observe_batch` uses — so "p99 over the last 500 ms", not
  "p99 since boot", at O(children) memory instead of O(observations).
- `GET /debug/timeseries?family=&window=` serves the ring as JSON on
  both HTTP servers (apiserver + cmd/scheduler), and `series()` /
  the same document embeds into the SOAK artifact.
- `evaluate_verdicts` runs a catalogue of named detectors over the
  series — monotonic RSS growth, windowed-p99 trend breach,
  activeQ/backlog divergence, watch-class materialization-rate
  collapse, fence-conflict spikes, watcher-lag tail growth — each
  yielding a machine-checkable verdict string ("what fell over first"),
  never silently skipped: a detector whose input families were not
  sampled reports `no-data` by name.

Correctness under concurrent writes: a scrape racing a counter inc or
a histogram observe must never produce a negative delta or a
non-monotone bucket window — deltas are clamped at zero and histogram
children are snapshotted under their own lock (the same lock
`observe_batch` takes, held for a list copy). Columns stay aligned
with the time axis: a child that first appears mid-run is backfilled
with NaN for the samples it missed.

The scraper's own cost is booked on `timeseries_scrape_seconds` /
`timeseries_samples_total` (it samples itself, like every other
family) and floored by the tier-1 overhead guard: the headline bench
with the scraper running must stay >= 0.95x the scraper-off run.
"""
from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from kubernetes_tpu import obs

SCRAPE_SECONDS = obs.gauge(
    "timeseries_scrape_seconds",
    "Wall cost of the most recent time-series registry sample (the "
    "scraper samples itself; the tier-1 overhead guard floors the "
    "headline bench with the scraper on at >= 0.95x off).")
SAMPLES_TOTAL = obs.counter(
    "timeseries_samples_total",
    "Registry samples taken by the in-process time-series scraper.")

#: default sample cadence (seconds) — two samples a second resolves
#: minute-scale degradation trends at ~720 samples per 6-minute ring
DEFAULT_INTERVAL = 0.5
#: default ring capacity (samples); newest-N win
DEFAULT_CAPACITY = 720


def _quantile(bounds: np.ndarray, cum: np.ndarray, count: int,
              q: float) -> float:
    """Quantile estimate from a cumulative bucket-delta window — the
    prometheus histogram_quantile shape: find the bucket the rank lands
    in with searchsorted, interpolate linearly inside it. Observations
    past the last finite bound clamp to it. NaN with an empty window."""
    if count <= 0:
        return float("nan")
    rank = q * count
    idx = int(np.searchsorted(cum, rank, side="left"))
    if idx >= len(bounds):
        return float(bounds[-1]) if len(bounds) else float("nan")
    hi = float(bounds[idx])
    lo = float(bounds[idx - 1]) if idx > 0 else 0.0
    c_hi = float(cum[idx])
    c_lo = float(cum[idx - 1]) if idx > 0 else 0.0
    if c_hi <= c_lo:
        return hi
    return lo + (hi - lo) * (rank - c_lo) / (c_hi - c_lo)


class TimeSeriesScraper:
    """Registry sampler with a bounded columnar ring (module docstring).

    Thread-safe: `sample()` may be driven by the background thread
    (`start()`/`stop()`) or called directly (tests, cooperative bench
    loops); `series()`/`to_artifact()` read a consistent snapshot."""

    def __init__(self, registry=None, capacity: int = DEFAULT_CAPACITY,
                 interval: float = DEFAULT_INTERVAL):
        self._registry = registry if registry is not None else obs.REGISTRY
        self.capacity = int(capacity)
        self.interval = float(interval)
        self._lock = threading.Lock()
        self._t: deque = deque(maxlen=self.capacity)     # perf_counter
        self._dt: deque = deque(maxlen=self.capacity)    # since prev sample
        #: (family, labelvalues, column) -> deque of floats, aligned _t
        self._cols: dict[tuple, deque] = {}
        #: family -> ("counter"|"gauge"|"histogram", labelnames)
        self._fams: dict[str, tuple] = {}
        #: (family, labelvalues) -> last cumulative snapshot
        self._prev: dict[tuple, object] = {}
        self._samples = 0
        self._t0: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- configuration -------------------------------------------------------
    def reset(self, capacity: Optional[int] = None,
              interval: Optional[float] = None) -> None:
        """Drop every sample and baseline (bench-cell isolation); the
        background thread, if any, keeps running on the new settings."""
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
            if interval is not None:
                self.interval = float(interval)
            self._t = deque(maxlen=self.capacity)
            self._dt = deque(maxlen=self.capacity)
            self._cols.clear()
            self._fams.clear()
            self._prev.clear()
            self._samples = 0
            self._t0 = None

    # -- sampling ------------------------------------------------------------
    def _col(self, key: tuple) -> deque:
        col = self._cols.get(key)
        if col is None:
            col = self._cols[key] = deque(maxlen=self.capacity)
            # a child born mid-run backfills NaN so every column stays
            # aligned with the time axis
            col.extend([float("nan")] * len(self._t))
        return col

    def sample(self, now: Optional[float] = None) -> int:
        """Take one sample of every family; returns the sample count."""
        t_in = time.perf_counter()
        now = t_in if now is None else now
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            dt = (now - self._t[-1]) if self._t else float("nan")
            touched: set = set()
            for fam in self._registry.families():
                kind = fam.kind
                if kind not in ("counter", "gauge", "histogram"):
                    continue
                self._fams[fam.name] = (kind, fam.labelnames)
                for labels, child in list(fam._children.items()):
                    base = (fam.name, labels)
                    if kind == "counter":
                        v = float(child.value)
                        prev = self._prev.get(base, v)
                        # clamp: a scrape racing an inc() must never
                        # book a negative delta
                        d = max(0.0, v - prev)
                        self._prev[base] = v
                        key = base + ("delta",)
                        self._col(key).append(d)
                        touched.add(key)
                    elif kind == "gauge":
                        try:
                            v = float(child.value)
                        except Exception:
                            # a raising callback gauge must not kill the
                            # sample; its column reads NaN this window
                            v = float("nan")
                        key = base + ("value",)
                        self._col(key).append(v)
                        touched.add(key)
                    else:
                        with child._lock:   # coherent (buckets,count,sum)
                            bks = list(child.buckets)
                            cnt = int(child.count)
                            sm = float(child.sum)
                        pb, pc, ps = self._prev.get(
                            base, (None, 0, 0.0))
                        if pb is None:
                            pb = [0] * len(bks)
                        self._prev[base] = (bks, cnt, sm)
                        cum = np.maximum(
                            np.asarray(bks, dtype=np.float64)
                            - np.asarray(pb, dtype=np.float64), 0.0)
                        # cumulative-bucket deltas stay non-decreasing
                        cum = np.maximum.accumulate(cum)
                        dc = max(0, cnt - pc)
                        bounds = np.asarray(child.bounds,
                                            dtype=np.float64)
                        for cname, val in (
                                ("count_delta", float(dc)),
                                ("sum_delta", max(0.0, sm - ps)),
                                ("p50", _quantile(bounds, cum, dc, 0.50)),
                                ("p99", _quantile(bounds, cum, dc, 0.99))):
                            key = base + (cname,)
                            self._col(key).append(val)
                            touched.add(key)
            # columns whose child vanished (registry cleared between
            # samples) pad NaN to stay aligned
            for key, col in self._cols.items():
                if key not in touched:
                    col.append(float("nan"))
            self._t.append(now)
            self._dt.append(dt)
            self._samples += 1
        SAMPLES_TOTAL.inc()
        SCRAPE_SECONDS.set(time.perf_counter() - t_in)
        return self._samples

    # -- background thread ---------------------------------------------------
    def start(self, interval: Optional[float] = None) -> None:
        """Run the sampler on a daemon thread at `interval` (idempotent)."""
        if interval is not None:
            self.interval = float(interval)
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.sample()
                except Exception:
                    # a sampling bug must never take down the process
                    # it is observing
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="timeseries-scraper")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=5.0)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- readout -------------------------------------------------------------
    @staticmethod
    def _label_str(labelnames, labelvalues) -> str:
        return ",".join(f'{k}="{v}"'
                        for k, v in zip(labelnames, labelvalues))

    @staticmethod
    def _round(xs) -> list:
        out = []
        for x in xs:
            if isinstance(x, float) and math.isnan(x):
                out.append(None)          # JSON-safe NaN
            else:
                out.append(round(float(x), 6))
        return out

    def series(self, family: Optional[str] = None,
               window: Optional[int] = None) -> dict:
        """The ring as one JSON-ready document: a relative time axis plus
        per-family, per-child columns (counters gain a derived `rate`
        column, histograms a `rate` from count deltas). `family` filters
        to one family; `window` keeps the newest N samples."""
        with self._lock:
            t0 = self._t0 if self._t0 is not None else 0.0
            ts = [round(x - t0, 3) for x in self._t]
            dts = list(self._dt)
            cols = {k: list(v) for k, v in self._cols.items()
                    if family is None or k[0] == family}
            fams = dict(self._fams)
            n_samples = self._samples
            interval = self.interval
        if window is not None and window > 0:
            ts = ts[-window:]
            dts = dts[-window:]
            cols = {k: v[-window:] for k, v in cols.items()}

        def rate(deltas):
            return [d / dt if (dt and not math.isnan(dt) and dt > 0
                               and not math.isnan(d)) else float("nan")
                    for d, dt in zip(deltas, dts)]

        out_fams: dict = {}
        for (fname, labels, cname), vals in sorted(cols.items()):
            kind, labelnames = fams.get(fname, ("untyped", ()))
            fam = out_fams.setdefault(fname, {"type": kind, "series": {}})
            key = self._label_str(labelnames, labels)
            ser = fam["series"].setdefault(key, {})
            ser[cname] = self._round(vals)
            if kind == "counter" and cname == "delta":
                ser["rate"] = self._round(rate(vals))
            elif kind == "histogram" and cname == "count_delta":
                ser["rate"] = self._round(rate(vals))
        return {"interval": interval, "samples": n_samples,
                "window": len(ts), "t": ts, "families": out_fams}

    def to_artifact(self) -> str:
        return json.dumps(self.series(), sort_keys=True)


#: the process-global scraper the /debug/timeseries routes serve — idle
#: (zero samples, no thread) until a bench cell or an operator starts it
SCRAPER = TimeSeriesScraper()


# -- verdict engine -----------------------------------------------------------

class SeriesView:
    """Detector-facing view over a `series()` document: per-sample
    column access with children summed elementwise (NaN-ignoring), plus
    the segment statistics every trend detector shares."""

    def __init__(self, doc: dict):
        self.doc = doc
        self.t = np.asarray(doc.get("t", ()), dtype=np.float64)

    def has(self, family: str) -> bool:
        return family in self.doc.get("families", {})

    def col(self, family: str, col: str) -> np.ndarray:
        """Elementwise sum of `col` across the family's children (the
        total rate/depth view); all-NaN rows stay NaN."""
        fam = self.doc.get("families", {}).get(family)
        n = len(self.t)
        if fam is None or n == 0:
            return np.full(n, np.nan)
        rows = []
        for ser in fam["series"].values():
            vals = ser.get(col)
            if vals is not None:
                rows.append([np.nan if v is None else float(v)
                             for v in vals])
        if not rows:
            return np.full(n, np.nan)
        arr = np.asarray(rows, dtype=np.float64)
        out = np.nansum(arr, axis=0)
        out[np.all(np.isnan(arr), axis=0)] = np.nan
        return out

    def rate(self, family: str) -> np.ndarray:
        return self.col(family, "rate")

    # -- segment statistics --------------------------------------------------
    @staticmethod
    def seg_mean(xs: np.ndarray, lo: float, hi: float) -> float:
        """NaN-ignoring mean of the [lo, hi) fraction of the series."""
        n = len(xs)
        if n == 0:
            return float("nan")
        seg = xs[int(lo * n):max(int(lo * n) + 1, int(hi * n))]
        if len(seg) == 0 or np.all(np.isnan(seg)):
            return float("nan")
        return float(np.nanmean(seg))

    @staticmethod
    def rising_frac(xs: np.ndarray) -> float:
        """Fraction of sample-to-sample deltas that are positive
        (NaN-pairs excluded) — the monotonic-trend signal."""
        d = np.diff(xs)
        d = d[~np.isnan(d)]
        if len(d) == 0:
            return 0.0
        return float(np.mean(d > 0))

    def valid(self, xs: np.ndarray) -> int:
        return int(np.sum(~np.isnan(xs)))

    def first_cross(self, xs: np.ndarray, threshold: float) -> Optional[float]:
        """Relative time of the first sample strictly above `threshold`
        (the "when did it fall over" stamp); None if never."""
        idx = np.flatnonzero(~np.isnan(xs) & (xs > threshold))
        if len(idx) == 0 or len(self.t) == 0:
            return None
        return float(self.t[int(idx[0])])


#: minimum valid samples before a trend detector renders judgment
_MIN_SAMPLES = 8


def _verdict(name: str, status: str, detail: str,
             breach_t: Optional[float] = None) -> dict:
    v = {"name": name, "status": status, "detail": detail,
         "verdict": f"{name}: {status.upper()} — {detail}"}
    if breach_t is not None:
        v["breach_t"] = round(breach_t, 3)
    return v


def _detect_rss_growth(view: SeriesView) -> dict:
    name = "rss-monotonic-growth"
    xs = view.col("process_resident_memory_bytes", "value")
    if view.valid(xs) < _MIN_SAMPLES or np.nanmax(xs) <= 0:
        return _verdict(name, "no-data",
                        "process_resident_memory_bytes not sampled")
    # skip the first quarter: arena growth during warmup/jit is expected
    n = len(xs)
    body = xs[n // 4:]
    head = SeriesView.seg_mean(body, 0.0, 0.25)
    tail = SeriesView.seg_mean(body, 0.75, 1.0)
    growth = tail - head
    rising = SeriesView.rising_frac(body)
    mb = 1024.0 * 1024.0
    if head > 0 and tail > 1.30 * head and growth > 128 * mb \
            and rising > 0.6:
        return _verdict(
            name, "fail",
            f"RSS grew {growth / mb:.0f} MiB ({tail / head:.2f}x) past "
            f"warmup with {rising:.0%} rising samples — leak-shaped",
            view.first_cross(xs, 1.30 * head))
    return _verdict(name, "pass",
                    f"RSS steady: {head / mb:.0f} -> {tail / mb:.0f} MiB "
                    f"past warmup ({rising:.0%} rising)")


def _detect_p99_trend(view: SeriesView, slo: float = 5.0) -> dict:
    name = "p99-trend-breach"
    xs = view.col("pod_startup_seconds_p99_windowed", "value")
    if view.valid(xs) < _MIN_SAMPLES or not np.any(np.nan_to_num(xs) > 0):
        return _verdict(name, "no-data",
                        "pod_startup_seconds_p99_windowed not sampled")
    head = SeriesView.seg_mean(xs, 0.0, 0.5)
    tail = SeriesView.seg_mean(xs, 0.75, 1.0)
    if tail > slo and head <= slo:
        return _verdict(
            name, "fail",
            f"windowed startup p99 breached the {slo:.0f}s SLO late: "
            f"first-half {head:.3f}s -> last-quarter {tail:.3f}s "
            "(cumulative gauges would have averaged this away)",
            view.first_cross(xs, slo))
    if tail > max(3.0 * head, head + 1.0) and tail > 0.5:
        return _verdict(
            name, "fail",
            f"windowed startup p99 trending up: {head:.3f}s -> "
            f"{tail:.3f}s ({tail / max(head, 1e-9):.1f}x)",
            view.first_cross(xs, max(3.0 * head, head + 1.0)))
    return _verdict(name, "pass",
                    f"windowed p99 {head:.3f}s -> {tail:.3f}s, "
                    f"SLO {slo:.0f}s held")


def _detect_activeq_divergence(view: SeriesView) -> dict:
    name = "activeq-divergence"
    depth = view.col("serve_activeq_depth", "value")
    if view.valid(depth) < _MIN_SAMPLES:
        return _verdict(name, "no-data", "serve_activeq_depth not sampled")
    head = SeriesView.seg_mean(depth, 0.0, 0.25)
    tail = SeriesView.seg_mean(depth, 0.75, 1.0)
    rising = SeriesView.rising_frac(depth)
    binds = view.rate("serve_pods_scheduled_total")
    b_head = SeriesView.seg_mean(binds, 0.0, 0.25)
    b_tail = SeriesView.seg_mean(binds, 0.75, 1.0)
    throughput_ramp = (not math.isnan(b_head) and not math.isnan(b_tail)
                       and b_tail > 2.0 * max(b_head, 1.0))
    threshold = 4.0 * max(head, 0.0) + 256.0
    if tail > threshold and rising > 0.6 and not throughput_ramp:
        return _verdict(
            name, "fail",
            f"activeQ/backlog diverging: depth {head:.0f} -> {tail:.0f} "
            f"({rising:.0%} rising) while bind rate went "
            f"{b_head:.0f} -> {b_tail:.0f}/s — arrivals outrunning the "
            "serve plane",
            view.first_cross(depth, threshold))
    return _verdict(name, "pass",
                    f"activeQ depth {head:.0f} -> {tail:.0f}, bind rate "
                    f"{b_head:.0f} -> {b_tail:.0f}/s")


def _detect_materialization_collapse(view: SeriesView) -> dict:
    name = "watch-materialization-collapse"
    mat = view.rate("watch_copyout_materializations_total")
    shared = view.rate("watch_copyout_shared_total")
    copyout = np.nansum(np.vstack([mat, shared]), axis=0) \
        if len(mat) else mat
    if view.valid(copyout) < _MIN_SAMPLES \
            or not np.any(np.nan_to_num(copyout) > 0):
        return _verdict(name, "no-data",
                        "watch copy-out counters not sampled (no shared "
                        "watch classes live)")
    # the write-rate reference: pod binds landing (present on every
    # serve/fleet path; commit waves are impl-specific)
    writes = view.rate("serve_pods_scheduled_total")
    peak = float(np.nanmax(copyout))
    tail = SeriesView.seg_mean(copyout, 0.75, 1.0)
    w_peak = float(np.nanmax(writes)) if view.valid(writes) else 0.0
    w_tail = SeriesView.seg_mean(writes, 0.75, 1.0)
    if peak > 0 and tail < 0.05 * peak and w_peak > 0 \
            and w_tail > 0.25 * w_peak:
        return _verdict(
            name, "fail",
            f"watch-class copy-out rate collapsed: peak {peak:.0f}/s -> "
            f"last-quarter {tail:.0f}/s while binds held "
            f"{w_tail:.1f}/s — watchers have stopped draining",
            None)
    return _verdict(name, "pass",
                    f"copy-out rate peak {peak:.0f}/s, last-quarter "
                    f"{tail:.0f}/s, bind rate {w_tail:.1f}/s")


def _detect_fence_spike(view: SeriesView) -> dict:
    name = "fence-conflict-spike"
    if not (view.has("store_fenced_writes_total")
            or view.has("fleet_bind_conflicts_total")):
        return _verdict(name, "no-data",
                        "fencing counters not sampled (no fleet live)")
    fenced = view.rate("store_fenced_writes_total")
    confl = view.rate("fleet_bind_conflicts_total")
    both = np.nansum(np.vstack([fenced, confl]), axis=0) \
        if len(fenced) else fenced
    if not np.any(np.nan_to_num(both) > 0):
        return _verdict(name, "pass",
                        "zero fenced writes / bind conflicts observed")
    base = SeriesView.seg_mean(both, 0.0, 0.75)
    tail = SeriesView.seg_mean(both, 0.75, 1.0)
    threshold = 10.0 * max(base, 0.1)
    if tail > threshold and tail > 1.0:
        return _verdict(
            name, "fail",
            f"fence-conflict rate spiked: {base:.2f}/s baseline -> "
            f"{tail:.2f}/s last quarter — claim churn or a zombie "
            "instance fighting the fence",
            view.first_cross(both, threshold))
    return _verdict(name, "pass",
                    f"fence conflicts bounded: {base:.2f}/s baseline, "
                    f"{tail:.2f}/s last quarter")


def _detect_watcher_lag_tail(view: SeriesView) -> dict:
    name = "watcher-lag-tail"
    xs = view.col("store_watcher_backlog_p99", "value")
    if view.valid(xs) < _MIN_SAMPLES:
        return _verdict(name, "no-data",
                        "store_watcher_backlog_p99 not sampled (no "
                        "watcher-lag gauges registered)")
    head = SeriesView.seg_mean(xs, 0.0, 0.25)
    tail = SeriesView.seg_mean(xs, 0.75, 1.0)
    rising = SeriesView.rising_frac(xs)
    threshold = 4.0 * max(head, 0.0) + 100.0
    if tail > threshold and rising > 0.6:
        return _verdict(
            name, "fail",
            f"watcher-lag tail growing: p99 backlog {head:.0f} -> "
            f"{tail:.0f} events ({rising:.0%} rising) — fan-out is "
            "outrunning the consumers",
            view.first_cross(xs, threshold))
    return _verdict(name, "pass",
                    f"watcher p99 backlog {head:.0f} -> {tail:.0f} "
                    "events, bounded")


#: the verdict catalogue — every entry is evaluated on every call (a
#: detector without data answers `no-data` BY NAME, never vanishes);
#: tests pin this set so a new detector cannot land unnamed
DETECTORS = {
    "rss-monotonic-growth": _detect_rss_growth,
    "p99-trend-breach": _detect_p99_trend,
    "activeq-divergence": _detect_activeq_divergence,
    "watch-materialization-collapse": _detect_materialization_collapse,
    "fence-conflict-spike": _detect_fence_spike,
    "watcher-lag-tail": _detect_watcher_lag_tail,
}


def evaluate_verdicts(source) -> dict:
    """Run every detector over a scraper (or a prebuilt `series()`
    document). Returns {"verdicts": [...], "first_failure": name|None}
    where `first_failure` is the failing detector with the earliest
    breach stamp — the soak's "what fell over first" headline."""
    doc = source.series() if hasattr(source, "series") else source
    view = SeriesView(doc)
    verdicts = []
    for name, fn in DETECTORS.items():
        try:
            verdicts.append(fn(view))
        except Exception as e:      # a broken detector is itself reported
            verdicts.append(_verdict(name, "error", f"detector raised: "
                                     f"{e!r}"))
    failures = [v for v in verdicts if v["status"] == "fail"]
    first = None
    if failures:
        stamped = [v for v in failures if v.get("breach_t") is not None]
        first = (min(stamped, key=lambda v: v["breach_t"])["name"]
                 if stamped else failures[0]["name"])
    return {"verdicts": verdicts, "first_failure": first}
