"""kubernetes_tpu.obs — shared observability layer.

One process-global metrics `Registry` (the component-base/metrics analog:
every layer registers labeled Counter/Gauge/Histogram families into it,
and any component's /metrics endpoint scrapes them all), plus span
tracing with Chrome trace-event export (`obs.trace`) and an exposition
lint helper (`obs.lint`).

Module-level helpers `counter()` / `gauge()` / `histogram()` are
get-or-create against the global registry, so modules declare their
families at import time and multiple component instances share children.
"""
from __future__ import annotations

from kubernetes_tpu.obs.registry import (   # noqa: F401
    Counter, Gauge, Histogram, MetricFamily, Registry,
    DEFAULT_BUCKETS, MICRO_BUCKETS, LATENCY_BUCKETS,
    escape_help, escape_label_value, format_value,
)
from kubernetes_tpu.obs import trace        # noqa: F401

#: the process-global registry every component wires into
REGISTRY = Registry()


def counter(name, help, labelnames=()):
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help, labelnames=()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help, labelnames=(), buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render_global() -> str:
    """One scrape of the global registry (every registered component)."""
    return REGISTRY.render()


# -- debug introspection registry (the /debug/sched surface) -----------------
# Components register named snapshot callables; `GET /debug/sched` (the
# apiserver and the scheduler command both serve it) collects every
# section into one JSON document. Sections use weakref-style callables
# that return None once their component is gone; a raising section reports
# its error instead of killing the whole endpoint.
_DEBUG_SOURCES: dict = {}


def register_debug(name: str, fn) -> None:
    """Register (or replace — latest wins) a named debug section."""
    _DEBUG_SOURCES[name] = fn


def unregister_debug(name: str) -> None:
    _DEBUG_SOURCES.pop(name, None)


def debug_snapshot() -> dict:
    out = {}
    for name, fn in list(_DEBUG_SOURCES.items()):
        try:
            snap = fn()
        except Exception as e:     # a broken section must not 500 the rest
            out[name] = {"error": repr(e)}
            continue
        if snap is not None:
            out[name] = snap
    return out


# the trace ring's overflow counter registers lazily from trace.py (it
# cannot import this package at its own import time); declare it eagerly
# here so the family is always present in the exposition
counter("obs_trace_dropped_total",
        "Spans dropped from the trace ring buffer on overflow (the "
        "ring keeps the newest spans; resize with "
        "obs.trace.set_capacity).")

# imported LAST: these modules register families against REGISTRY above.
# procmetrics registers the process self-metrics (RSS/fds/threads/gc
# pauses) EAGERLY so the time-series scraper sees them from sample 0;
# timeseries hangs the scraper + verdict engine off the same registry.
from kubernetes_tpu.obs import ledger       # noqa: F401,E402
from kubernetes_tpu.obs import flight       # noqa: F401,E402
from kubernetes_tpu.obs import procmetrics  # noqa: F401,E402
from kubernetes_tpu.obs import timeseries   # noqa: F401,E402
