"""kubernetes_tpu.obs — shared observability layer.

One process-global metrics `Registry` (the component-base/metrics analog:
every layer registers labeled Counter/Gauge/Histogram families into it,
and any component's /metrics endpoint scrapes them all), plus span
tracing with Chrome trace-event export (`obs.trace`) and an exposition
lint helper (`obs.lint`).

Module-level helpers `counter()` / `gauge()` / `histogram()` are
get-or-create against the global registry, so modules declare their
families at import time and multiple component instances share children.
"""
from __future__ import annotations

from kubernetes_tpu.obs.registry import (   # noqa: F401
    Counter, Gauge, Histogram, MetricFamily, Registry,
    DEFAULT_BUCKETS, escape_help, escape_label_value, format_value,
)
from kubernetes_tpu.obs import trace        # noqa: F401

#: the process-global registry every component wires into
REGISTRY = Registry()


def counter(name, help, labelnames=()):
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help, labelnames=()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help, labelnames=(), buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def render_global() -> str:
    """One scrape of the global registry (every registered component)."""
    return REGISTRY.render()
