"""Shared metrics registry — the component-metrics analog.

The reference ships a Prometheus registry on every component
(k8s.io/component-base/metrics; /metrics on the apiserver, scheduler,
controller-manager, kubelet). This is that layer for the repro: labeled
Counter/Gauge/Histogram families registered once per process, rendered in
the Prometheus text exposition format (version 0.0.4) with proper label
escaping — replacing the hand-rolled scheduler-only renderer that
interpolated label values unescaped.

Families are get-or-create by name (`Registry.counter(...)` returns the
existing family on a repeat call with the same shape), so modules declare
their metrics at import time and any number of component instances share
them — exactly how the prometheus client's default registry behaves.
"""
from __future__ import annotations

import re
import threading
from typing import Callable, Iterable, Optional, Sequence

# reference buckets: ExponentialBuckets(0.001, 2, 15) (metrics.go:93)
DEFAULT_BUCKETS = tuple(0.001 * 2 ** i for i in range(15))
# µs-scale preset for the native commit/fan-out phases: the default
# ms-scale ladder starts at 1ms, which crushes a 5-30µs commit-core call
# or a sub-ms watch fan-out lag into the first bucket — these start at 1µs
# and reach ~4s (ExponentialBuckets(1e-6, 4, 12) shape)
MICRO_BUCKETS = tuple(1e-6 * 4 ** i for i in range(12))
# wide pod-lifecycle preset: one family spans µs-scale phases (commit,
# fan-out copy-out) AND seconds-scale phases (queue wait) — 1µs..134s
LATENCY_BUCKETS = tuple(1e-6 * 4 ** i for i in range(14))

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, double-quote, and
    newline must be escaped inside `{key="..."}` (exposition format §label
    values) — the old renderer interpolated them raw."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(v: str) -> str:
    """HELP text escaping: backslash and newline (quotes are legal)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v) -> str:
    """Integral values render without a decimal point (counters read as
    event counts); everything else as shortest float repr. NaN and the
    infinities use the Prometheus text-format spellings — callback
    gauges publish NaN as the no-data value (a dead component's reader,
    a lane that committed nothing), and the exposition must carry that
    through rather than crash the whole scrape on int(NaN)."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_suffix(names: Sequence[str], values: Sequence[str],
                   extra: str = "") -> str:
    pairs = [f'{k}="{escape_label_value(v)}"'
             for k, v in zip(names, values)]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class _GaugeChild:
    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Callback gauge: the value is read at collect time (the
        prometheus GaugeFunc analog) — for queue depths / cache sizes."""
        self._fn = fn

    @property
    def value(self) -> float:
        return float(self._fn()) if self._fn is not None else self._value


class _HistogramChild:
    __slots__ = ("bounds", "buckets", "count", "sum", "_lock")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = bounds
        self.buckets = [0] * len(bounds)
        self.count = 0
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        self.observe_many(value, 1)

    def observe_many(self, value: float, count: int) -> None:
        """`count` identical observations in one pass (burst commits record
        their per-pod share without N bucket walks)."""
        if count <= 0:
            return
        with self._lock:
            self.count += count
            self.sum += value * count
            for i, b in enumerate(self.bounds):
                if value <= b:
                    self.buckets[i] += count

    def observe_batch(self, values) -> None:
        """Observe a whole batch of DISTINCT values in one vectorized pass —
        the watch fan-out copy-out and the per-wave ledger folds observe
        thousands of values per call; a Python observe() loop there would
        put an O(events) bucket walk back on the consumer threads."""
        import numpy as _np
        arr = _np.asarray(values, dtype=_np.float64)
        if arr.size == 0:
            return
        bounds = _np.asarray(self.bounds, dtype=_np.float64)
        # first bucket each value lands in; counts cumulate left-to-right
        # (bucket[i] counts v <= bounds[i], the Prometheus cumulative shape)
        idx = _np.searchsorted(bounds, arr, side="left")
        hist = _np.bincount(idx, minlength=len(bounds) + 1)
        cum = _np.cumsum(hist[:len(bounds)])
        with self._lock:
            self.count += int(arr.size)
            self.sum += float(arr.sum())
            for i in range(len(self.bounds)):
                self.buckets[i] += int(cum[i])


_CHILD_TYPES = {"counter": _CounterChild, "gauge": _GaugeChild}


class MetricFamily:
    """One named family: HELP + TYPE + children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def _new_child(self):
        return _CHILD_TYPES[self.kind]()

    def labels(self, *values, **kv):
        """Get-or-create the child for one label-value combination.
        Accepts positional values (labelnames order) or keywords."""
        if kv:
            if values:
                raise ValueError("mix of positional and keyword labels")
            values = tuple(str(kv[ln]) for ln in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._new_child())
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} has labels; use .labels(...)")
        return self.labels()

    # -- exposition ---------------------------------------------------------
    def header_lines(self) -> list[str]:
        return [f"# HELP {self.name} {escape_help(self.help)}",
                f"# TYPE {self.name} {self.kind}"]

    def sample_lines(self) -> list[str]:
        out = []
        for values in sorted(self._children):
            child = self._children[values]
            suffix = _labels_suffix(self.labelnames, values)
            out.append(f"{self.name}{suffix} {format_value(child.value)}")
        return out

    def render(self) -> list[str]:
        return self.header_lines() + self.sample_lines()


class Counter(MetricFamily):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(MetricFamily):
    kind = "gauge"

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default().set_function(fn)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(MetricFamily):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def observe_many(self, value: float, count: int) -> None:
        self._default().observe_many(value, count)

    def observe_batch(self, values) -> None:
        self._default().observe_batch(values)

    def sample_lines(self) -> list[str]:
        out = []
        for values in sorted(self._children):
            child = self._children[values]
            for i, b in enumerate(self.buckets):
                le = 'le="%g"' % b
                sfx = _labels_suffix(self.labelnames, values, le)
                out.append(f"{self.name}_bucket{sfx} {child.buckets[i]}")
            sfx = _labels_suffix(self.labelnames, values, 'le="+Inf"')
            out.append(f"{self.name}_bucket{sfx} {child.count}")
            sfx = _labels_suffix(self.labelnames, values)
            out.append(f"{self.name}_sum{sfx} {child.sum:.6f}")
            out.append(f"{self.name}_count{sfx} {child.count}")
        return out


class Registry:
    """Ordered set of metric families; renders one /metrics scrape."""

    def __init__(self):
        self._families: dict[str, MetricFamily] = {}
        self._lock = threading.Lock()

    def register(self, family: MetricFamily) -> MetricFamily:
        with self._lock:
            existing = self._families.get(family.name)
            if existing is not None:
                raise ValueError(f"metric {family.name!r} already registered")
            self._families[family.name] = family
        return family

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> MetricFamily:
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if not isinstance(existing, cls) \
                        or existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type or label set")
                want = kw.get("buckets")
                if want is not None and tuple(want) != DEFAULT_BUCKETS \
                        and existing.buckets != tuple(sorted(want)):
                    # per-family bucket overrides are part of the family's
                    # shape: silently returning the old ladder is how a
                    # µs-scale family ends up crushed into one ms bucket.
                    # (Passing the default ladder means "no opinion", so a
                    # declare-without-buckets reuse keeps working.)
                    raise ValueError(
                        f"metric {name!r} re-registered with different "
                        f"buckets")
                return existing
            fam = cls(name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str,
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str,
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str,
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def families(self) -> Iterable[MetricFamily]:
        return list(self._families.values())

    def render(self) -> str:
        lines: list[str] = []
        for fam in self._families.values():
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        """Drop every family (test isolation helper)."""
        with self._lock:
            self._families.clear()
