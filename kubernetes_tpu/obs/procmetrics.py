"""Process self-metrics — the leak-detection families (round 21).

A soak's first casualty is usually the process itself: a heap that only
grows, a file-descriptor leak from un-stopped watches, a thread that
never joins, or cyclic-GC pauses landing inside window prologues (round
17 measured 127 ms gen2 passes exactly there). None of that was visible
without attaching a profiler. This module registers the standard
process-health families EAGERLY (imported from `obs.__init__`, before
any component), so the time-series scraper sees them from sample 0 and
the soak verdict engine can run its monotonic-RSS detector over a full
trajectory:

- ``process_resident_memory_bytes`` / ``process_virtual_memory_bytes``
  — callback gauges read from /proc/self/status (VmRSS / VmSize); 0 on
  platforms without procfs (the scrape must never fail);
- ``process_open_fds`` — len(/proc/self/fd) at collect time (watch
  leaks show up here long before accept() starts failing);
- ``process_threads`` — threading.active_count() (fleet drivers,
  watcher drainers, and scraper threads must come back down after a
  cell);
- ``python_gc_pause_seconds{generation}`` — a histogram fed by
  `gc.callbacks` ("start"/"stop" bracket every collection): the
  stop-the-world pauses the round-17 GC posture defers, now measurable
  without a profiler. Installed once per process; `install()` is
  idempotent and `uninstall()` exists for test isolation.

Everything here must stay allocation-light: the gauges are read on
every /metrics render AND every scraper sample (default 2 Hz in a
soak), and the gc callback runs inside the collector's pause.
"""
from __future__ import annotations

import gc
import os
import threading
import time
from typing import Optional

from kubernetes_tpu import obs
from kubernetes_tpu.obs.registry import MICRO_BUCKETS

_PAGE = 1024  # /proc/self/status reports kB


def _status_kb(field: str) -> float:
    """Read one `Vm*` field (kB) from /proc/self/status; 0.0 when the
    platform has no procfs or the field is absent."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(field.encode()):
                    return float(line.split()[1]) * _PAGE
    except OSError:
        pass
    return 0.0


def resident_memory_bytes() -> float:
    return _status_kb("VmRSS:")


def virtual_memory_bytes() -> float:
    return _status_kb("VmSize:")


def open_fds() -> float:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


RSS = obs.gauge(
    "process_resident_memory_bytes",
    "Resident set size of this process (VmRSS from /proc/self/status; "
    "0 without procfs). The soak verdict engine's monotonic-growth "
    "detector reads this series.")
RSS.set_function(resident_memory_bytes)

VSZ = obs.gauge(
    "process_virtual_memory_bytes",
    "Virtual memory size of this process (VmSize from /proc/self/status; "
    "0 without procfs).")
VSZ.set_function(virtual_memory_bytes)

OPEN_FDS = obs.gauge(
    "process_open_fds",
    "Open file descriptors (len of /proc/self/fd; 0 without procfs). "
    "Un-stopped watches and leaked sockets show up here long before "
    "accept() starts failing.")
OPEN_FDS.set_function(open_fds)

THREADS = obs.gauge(
    "process_threads",
    "Live Python threads (threading.active_count()): fleet drivers, "
    "watcher drainers, and scraper threads must come back down after a "
    "bench cell.")
THREADS.set_function(lambda: float(threading.active_count()))

GC_PAUSE = obs.histogram(
    "python_gc_pause_seconds",
    "Cyclic-GC collection pauses by generation, bracketed via "
    "gc.callbacks (start->stop). The round-17 serve cells measured "
    "~127 ms gen2 passes landing as window-prologue stalls; this makes "
    "that visible without a profiler.",
    ("generation",), buckets=MICRO_BUCKETS)

GC_COLLECTED = obs.counter(
    "python_gc_collected_total",
    "Objects reclaimed by the cyclic collector, by generation (from the "
    "gc callback's info dict).", ("generation",))

# -- gc.callbacks bracket -----------------------------------------------------
# one slot per generation: gc is not reentrant per generation, and the
# callback runs inside the collector's stop-the-world pause — keep it to
# a clock read and a dict store
_gc_start: dict[int, float] = {}
_installed = False


def _gc_callback(phase: str, info: dict) -> None:
    gen = info.get("generation", 0)
    if phase == "start":
        _gc_start[gen] = time.perf_counter()
        return
    t0 = _gc_start.pop(gen, None)
    if t0 is not None:
        GC_PAUSE.labels(str(gen)).observe(time.perf_counter() - t0)
    collected = info.get("collected", 0)
    if collected:
        GC_COLLECTED.labels(str(gen)).inc(collected)


def install() -> None:
    """Attach the gc pause bracket (idempotent)."""
    global _installed
    if _installed:
        return
    gc.callbacks.append(_gc_callback)
    _installed = True


def uninstall() -> None:
    """Detach the bracket (test isolation)."""
    global _installed
    if _installed:
        try:
            gc.callbacks.remove(_gc_callback)
        except ValueError:
            pass
        _installed = False
        _gc_start.clear()


install()
