"""Exposition-format lint — the promlint analog for the repro's /metrics.

`lint_exposition(text)` parses one Prometheus text-format scrape and
returns a list of problems (empty = clean). Checked invariants:

- line syntax: every sample parses as `name{labels} value`;
- HELP/TYPE precede their family's samples, at most one of each, TYPE is a
  known type, and a family's samples are contiguous (no interleaving);
- label syntax: valid label names, quoted values with only legal escapes
  (\\\\, \\", \\n) — an unescaped quote/newline shows up here as a parse
  failure;
- histogram consistency: per child, bucket counts monotonically
  non-decreasing as `le` ascends, a `+Inf` bucket present and equal to
  `_count`, `_sum` and `_count` present.

Used by the tier-1 exposition tests (a live APIServer scrape runs through
this) so a regression in any family's rendering fails `pytest tests/ -q`.
"""
from __future__ import annotations

import re

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME}) (.*)$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (\w+)$")
_SAMPLE_RE = re.compile(rf"^({_NAME})(\{{.*\}})? (.+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|\\n)*)"')
_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_HIST_SUFFIX = re.compile(r"^(.*)_(bucket|sum|count)$")


def _parse_labels(block: str):
    """`{k="v",...}` -> dict or None on malformed/partially-escaped input."""
    inner = block[1:-1]
    out = {}
    pos = 0
    while pos < len(inner):
        m = _LABEL_RE.match(inner, pos)
        if m is None:
            return None
        out[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(inner):
            if inner[pos] != ",":
                return None
            pos += 1
    return out


def _family_of(name: str, types: dict) -> str:
    """Map a sample name to its family (histogram suffixes fold in)."""
    m = _HIST_SUFFIX.match(name)
    if m and types.get(m.group(1)) == "histogram":
        return m.group(1)
    return name


def lint_exposition(text: str) -> list[str]:
    problems: list[str] = []
    helps: dict[str, int] = {}
    types: dict[str, str] = {}
    closed: set[str] = set()        # families whose sample run ended
    current: str | None = None
    # histogram state: family -> {labelkey -> {"buckets": [(le, v)],
    #                                          "sum": x, "count": n}}
    hist: dict[str, dict] = {}

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            hm, tm = _HELP_RE.match(line), _TYPE_RE.match(line)
            if hm is None and tm is None:
                if line.startswith(("# HELP", "# TYPE")):
                    problems.append(f"line {lineno}: malformed comment: "
                                    f"{line!r}")
                continue
            name = (hm or tm).group(1)
            if hm is not None:
                if name in helps:
                    problems.append(f"line {lineno}: duplicate HELP for "
                                    f"{name}")
                helps[name] = lineno
            else:
                if name in types:
                    problems.append(f"line {lineno}: duplicate TYPE for "
                                    f"{name}")
                elif tm.group(2) not in _TYPES:
                    problems.append(f"line {lineno}: unknown TYPE "
                                    f"{tm.group(2)!r} for {name}")
                types[name] = tm.group(2)
            if name in closed:
                problems.append(f"line {lineno}: HELP/TYPE for {name} after "
                                f"its samples ended")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        name, labels_block, value = m.group(1), m.group(2), m.group(3)
        labels = {}
        if labels_block:
            labels = _parse_labels(labels_block)
            if labels is None:
                problems.append(f"line {lineno}: malformed/unescaped labels "
                                f"in {line!r}")
                continue
        try:
            val = float(value)
        except ValueError:
            problems.append(f"line {lineno}: non-numeric value {value!r}")
            continue
        family = _family_of(name, types)
        if family != current:
            if family in closed:
                problems.append(f"line {lineno}: samples for {family} are "
                                f"not contiguous")
            if current is not None:
                closed.add(current)
            current = family
        if types.get(family) == "histogram":
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            st = hist.setdefault(family, {}).setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            if name.endswith("_bucket"):
                le = labels.get("le")
                if le is None:
                    problems.append(f"line {lineno}: {name} without le label")
                else:
                    st["buckets"].append(
                        (float("inf") if le == "+Inf" else float(le), val))
            elif name.endswith("_sum"):
                st["sum"] = val
            elif name.endswith("_count"):
                st["count"] = val
            else:
                problems.append(f"line {lineno}: stray sample {name} in "
                                f"histogram family {family}")

    for family, children in hist.items():
        for key, st in children.items():
            where = f"{family}{dict(key) if key else ''}"
            bks = st["buckets"]
            if not bks:
                problems.append(f"{where}: histogram child with no buckets")
                continue
            les = [le for le, _ in bks]
            if les != sorted(les):
                problems.append(f"{where}: bucket le values not ascending")
            vals = [v for _, v in sorted(bks)]
            if any(prev > nxt for prev, nxt in zip(vals, vals[1:])):
                problems.append(f"{where}: bucket counts not monotonic")
            if les[-1] != float("inf"):
                problems.append(f"{where}: missing +Inf bucket")
            if st["count"] is None:
                problems.append(f"{where}: missing _count")
            elif les[-1] == float("inf") and bks[-1][1] != st["count"]:
                problems.append(f"{where}: +Inf bucket {bks[-1][1]} != "
                                f"_count {st['count']}")
            if st["sum"] is None:
                problems.append(f"{where}: missing _sum")

    return problems
