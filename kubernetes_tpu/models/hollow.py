"""Hollow cluster generators — the kubemark analog.

Mirrors pkg/kubemark (hollow_kubelet.go:44 — real control-plane-visible
nodes with fake substance) and test/utils/runners.go NodePreparer
strategies: thousands of realistic nodes (zones, labels, capacity shapes)
and pod-creation strategies, sourced straight into the store so control
plane scale is testable without machines.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Iterable, Optional

from kubernetes_tpu.api.types import (
    Node, Pod, Container, ContainerPort, Taint, Toleration, Affinity,
    PodAffinity, PodAntiAffinity, PodAffinityTerm, WeightedPodAffinityTerm,
    NodeAffinity, NodeSelectorTerm, PreferredSchedulingTerm, Requirement,
    LabelSelector, IN,
    LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION, LABEL_HOSTNAME, NO_SCHEDULE,
)
from kubernetes_tpu import obs
from kubernetes_tpu.store.store import Store, NODES, PODS

GI = 1024 ** 3
MI = 1024 ** 2

# node heartbeat observability (kubelet nodelease controller analog):
# registered at import so /metrics exposes the family before the first
# heartbeat — a fleet whose renewals stop is visible as a flat counter
LEASE_RENEWS = obs.counter(
    "node_lease_renew_total",
    "Node heartbeat Lease renewals by outcome: renewed (CAS on the "
    "existing record), created (first heartbeat), failed (the store "
    "rejected the write — the node will grade Unknown after the "
    "monitor grace period).", ("outcome",))

# the scheduler_perf node shape (reference: scheduler_test.go:49-64)
PERF_NODE_CPU = 4000
PERF_NODE_MEM = 32 * GI
PERF_NODE_PODS = 110


@dataclass
class NodeStrategy:
    """TestNodePreparer analog: how to shape a batch of hollow nodes."""
    count: int
    cpu: int = PERF_NODE_CPU
    mem: int = PERF_NODE_MEM
    pods: int = PERF_NODE_PODS
    zones: int = 0                 # 0 = unzoned
    region: str = "region-1"
    label_fracs: dict = field(default_factory=dict)   # label -> (value, fraction)
    taint_frac: float = 0.0
    taint: Optional[Taint] = None
    name_prefix: str = "hollow-node"


def make_hollow_nodes(strategy: NodeStrategy, seed: int = 0,
                      start_index: int = 0) -> list[Node]:
    rng = random.Random(seed)
    nodes = []
    for i in range(start_index, start_index + strategy.count):
        name = f"{strategy.name_prefix}-{i}"
        labels = {LABEL_HOSTNAME: name}
        if strategy.zones:
            labels[LABEL_ZONE_FAILURE_DOMAIN] = f"zone-{i % strategy.zones}"
            labels[LABEL_ZONE_REGION] = strategy.region
        for key, (value, frac) in strategy.label_fracs.items():
            if rng.random() < frac:
                labels[key] = value
        taints = ()
        if strategy.taint is not None and rng.random() < strategy.taint_frac:
            taints = (strategy.taint,)
        nodes.append(Node(
            name=name, labels=labels, taints=taints,
            allocatable={"cpu": strategy.cpu, "memory": strategy.mem,
                         "pods": strategy.pods}))
    return nodes


@dataclass
class PodStrategy:
    """TestPodCreator strategy analog (test/utils/runners.go)."""
    count: int
    cpu: int = 100                 # milli
    mem: int = 500 * MI
    name_prefix: str = "pod"
    namespace: str = "default"
    labels: dict = field(default_factory=lambda: {"app": "density"})
    # feature knobs matching scheduler_bench_test.go matrices
    anti_affinity_topology: Optional[str] = None   # e.g. hostname label
    affinity_topology: Optional[str] = None
    node_affinity_key: Optional[str] = None
    node_affinity_values: tuple = ()
    host_port: int = 0
    tolerations: tuple = ()
    priority: int = 0


def make_pods(strategy: PodStrategy, start_index: int = 0) -> list[Pod]:
    pods = []
    for j in range(start_index, start_index + strategy.count):
        kw = {}
        affinity_parts = {}
        if strategy.anti_affinity_topology:
            term = PodAffinityTerm(
                label_selector=LabelSelector.from_dict(dict(strategy.labels)),
                topology_key=strategy.anti_affinity_topology)
            affinity_parts["pod_anti_affinity"] = PodAntiAffinity(required=(term,))
        if strategy.affinity_topology:
            term = PodAffinityTerm(
                label_selector=LabelSelector.from_dict(dict(strategy.labels)),
                topology_key=strategy.affinity_topology)
            affinity_parts["pod_affinity"] = PodAffinity(required=(term,))
        if strategy.node_affinity_key:
            affinity_parts["node_affinity"] = NodeAffinity(
                required=(NodeSelectorTerm(match_expressions=(
                    Requirement(key=strategy.node_affinity_key, op=IN,
                                values=strategy.node_affinity_values),)),))
        if affinity_parts:
            kw["affinity"] = Affinity(**affinity_parts)
        ports = ()
        if strategy.host_port:
            ports = (ContainerPort(host_port=strategy.host_port,
                                   container_port=strategy.host_port),)
        pods.append(Pod(
            name=f"{strategy.name_prefix}-{j}",
            namespace=strategy.namespace,
            labels=dict(strategy.labels),
            tolerations=strategy.tolerations,
            priority=strategy.priority,
            containers=(Container.make(
                name="c", requests={"cpu": strategy.cpu, "memory": strategy.mem},
                ports=ports),),
            **kw))
    return pods


def populate_store(store: Store, node_strategies: Iterable[NodeStrategy],
                   existing_pod_strategies: Iterable[PodStrategy] = (),
                   seed: int = 0) -> tuple[int, int]:
    """Load hollow nodes (and optionally pre-placed pods) into the store.
    Pre-placed pods are spread round-robin across the nodes with node_name
    already set, like the benchmark's 'existing pods' population."""
    all_nodes = []
    idx = 0
    for st in node_strategies:
        batch = make_hollow_nodes(st, seed=seed, start_index=idx)
        idx += st.count
        all_nodes.extend(batch)
        for n in batch:
            store.create(NODES, n)
    placed = 0
    pidx = 0
    for ps in existing_pod_strategies:
        for pod in make_pods(ps, start_index=pidx):
            pod.node_name = all_nodes[placed % len(all_nodes)].name
            store.create(PODS, pod)
            placed += 1
        pidx += ps.count
    return len(all_nodes), placed


class HollowKubelet:
    """Hollow node agent — pkg/kubemark/hollow_kubelet.go:44 plus the node
    heartbeat the real kubelet performs (NodeLease renewal + Ready status,
    pkg/kubelet nodelease/nodestatus): each heartbeat() CASes the node's
    Lease record and asserts Ready=True on the Node through the store.
    `stop()` silences it — the failure-injection switch: the node-lifecycle
    controller's health monitor then grades the node Unknown, taints it,
    and evicts its pods."""

    def __init__(self, store: Store, node_name: str, clock=None):
        from kubernetes_tpu.utils.clock import RealClock
        self.store = store
        self.node_name = node_name
        self.clock = clock or RealClock()
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def heartbeat(self, pods=None) -> None:
        """One kubelet sync tick: renew the node lease, assert node Ready,
        and 'run' the pods bound here (hollow_kubelet.go's fake runtime:
        Pending pods become Running with Ready=True and a start time — the
        status the disruption controller's healthy count and the reference's
        IsPodReady read).

        A fleet driving thousands of hollow kubelets must store.list(PODS)
        ONCE per round and pass the result as `pods` — otherwise each
        heartbeat lists (clones) the whole pod set itself, making one fleet
        round O(nodes x pods) in pod clones."""
        if self._stopped:
            return
        from kubernetes_tpu.api.types import Lease, NodeCondition, \
            node_lease_key
        from kubernetes_tpu.store.store import LEASES, NotFoundError
        now = self.clock.now()
        self._run_pods(now, pods)
        lease_key = node_lease_key(self.node_name)
        try:
            def renew(lease):
                lease.holder = self.node_name
                lease.renew_time = now
                return lease
            self.store.guaranteed_update(LEASES, lease_key, renew)
            LEASE_RENEWS.labels("renewed").inc()
        except NotFoundError:
            try:
                self.store.create(LEASES, Lease(
                    name=lease_key, holder=self.node_name,
                    acquire_time=now, renew_time=now))
                LEASE_RENEWS.labels("created").inc()
            except Exception:   # lost a create race / store fault
                LEASE_RENEWS.labels("failed").inc()
        except Exception:       # transport/store fault: next tick retries
            LEASE_RENEWS.labels("failed").inc()

        def set_ready(node):
            conds = [c for c in node.conditions if c.type != "Ready"]
            conds.append(NodeCondition(type="Ready", status="True"))
            new = tuple(conds)
            if new == node.conditions:
                return None
            node.conditions = new
            return node
        try:
            self.store.guaranteed_update(NODES, self.node_name, set_ready,
                                         allow_skip=True)
        except NotFoundError:
            pass

    def _run_pods(self, now: float, pods=None) -> None:
        from kubernetes_tpu.api.types import PodCondition
        from kubernetes_tpu.store.store import NotFoundError
        if pods is None:
            pods, _rv = self.store.list(PODS)
        for pod in pods:
            if pod.node_name != self.node_name or pod.deleted \
                    or pod.phase != "Pending":
                continue

            def run(cur, _now=now):
                if cur.phase != "Pending" or not cur.node_name:
                    return None
                cur.phase = "Running"
                cur.start_time = _now
                conds = [c for c in cur.conditions if c.type != "Ready"]
                conds.append(PodCondition(type="Ready", status="True"))
                cur.conditions = tuple(conds)
                return cur
            try:
                self.store.guaranteed_update(PODS, pod.key, run,
                                             allow_skip=True)
            except NotFoundError:
                continue


class HollowProxy:
    """Hollow kube-proxy — pkg/kubemark/hollow_proxy.go:40 over the
    userspace proxier's data structure: an event-driven service -> backends
    routing table fed by Endpoints watches (the reference programs
    iptables/IPVS from the same inputs; with no kernel here, the table IS
    the dataplane). `route(service)` round-robins across ready backends
    like the userspace proxier's LoadBalancerRR."""

    def __init__(self, store: Store):
        from kubernetes_tpu.store.informer import InformerFactory
        from kubernetes_tpu.store.store import ENDPOINTS
        self.store = store
        self.informers = InformerFactory(store)
        self._table: dict[str, tuple] = {}
        self._rr: dict[str, int] = {}
        eps = self.informers.informer(ENDPOINTS)
        eps.add_event_handler(
            on_add=lambda e: self._table.__setitem__(e.key, e.addresses),
            on_update=lambda o, n: self._table.__setitem__(n.key, n.addresses),
            on_delete=lambda e: (self._table.pop(e.key, None),
                                 self._rr.pop(e.key, None)))

    def sync(self) -> None:
        self.informers.sync_all()
        from kubernetes_tpu.store.store import ENDPOINTS
        for e in self.informers.informer(ENDPOINTS).list():
            self._table[e.key] = e.addresses

    def pump(self) -> int:
        return self.informers.pump_all()

    def backends(self, service_key: str) -> tuple:
        return self._table.get(service_key, ())

    def route(self, service_key: str):
        """(pod_key, node_name) of the next backend, or None when the
        service has no ready endpoints."""
        backends = self._table.get(service_key)
        if not backends:
            return None
        i = self._rr.get(service_key, 0) % len(backends)
        self._rr[service_key] = i + 1
        return backends[i]
