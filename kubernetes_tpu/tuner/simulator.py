"""Offline simulator — recorded worlds replayed under a candidate row.

A `SimWorld` is one flight-recorder burst capture (round 12's replay
mode): the pre-burst NodeInfo clones, the NodeTree cursor state, the
service/replicaset lists, and the pod segments — everything that
determined the live decision. `simulate(world, candidate)` re-runs the
world through the SAME pure-Python oracle the parity replay uses, but
with the candidate's priority weights substituted, then scores the
resulting placements with a deterministic reward.

Determinism is the contract the search stands on: the oracle has no RNG,
the worlds are frozen clones, and every reward term is a pure function
of the final placements — same worlds + same candidate => identical
reward, bit-for-bit, across processes. (The CEM's only randomness is its
own seeded sampler.)

The reward is a placement-quality objective, largest term first:
- placed fraction (a row that strands pods loses outright),
- packing utilization: mean cpu fill of the nodes the burst USED —
  the `cluster_resource_utilization` satellite's per-decision twin
  (bin-packing rows concentrate load, spread rows dilute it),
- zone spread: 1 - (max-min)/placed over per-zone placement counts
  (tie-breaker so pure packing doesn't collapse a zone),
- gang locality: modal-zone fraction over each gang segment (the
  round-19 rank-aware objective, scored on the outcome).
"""
from __future__ import annotations

from typing import Optional

REWARD_PLACED = 1000.0
REWARD_PACK = 100.0
REWARD_SPREAD = 10.0
REWARD_LOCALITY = 10.0


class SimWorld:
    """One recorded burst, frozen for candidate replays."""

    __slots__ = ("infos", "tree_snap", "services", "replicasets", "pct",
                 "hpaw", "enabled", "segments", "names", "li", "lni",
                 "kind")

    def __init__(self, infos, tree_snap, services, replicasets, pct,
                 hpaw, enabled, segments, names, li, lni, kind):
        self.infos = infos            # {name: NodeInfo} (already clones)
        self.tree_snap = tree_snap    # FlightRecorder tree snapshot dict
        self.services = services
        self.replicasets = replicasets
        self.pct = pct
        self.hpaw = hpaw
        self.enabled = enabled
        self.segments = segments      # [(pods, is_gang), ...]
        self.names = names            # first enumeration of the burst
        self.li = li
        self.lni = lni
        self.kind = kind

    @staticmethod
    def from_record(rec) -> "SimWorld":
        """Build a world from a replay-mode BurstRecord. The record's
        capture is shared read-only; simulate() clones per candidate."""
        if rec.capture is None:
            raise ValueError("record has no replay capture "
                             "(RECORDER.configure(mode='replay') first)")
        if rec.kind not in ("uniform", "scan", "fused"):
            raise ValueError(f"{rec.kind} records are dump-only")
        cap = rec.capture
        return SimWorld(
            infos=cap["infos"], tree_snap=cap["tree"],
            services=cap["services"], replicasets=cap["replicasets"],
            pct=cap["pct"], hpaw=cap["hpaw"], enabled=cap["enabled"],
            segments=rec.segments, names=list(rec.names),
            li=rec.li, lni=rec.lni, kind=rec.kind)

    @property
    def n_pods(self) -> int:
        return sum(len(seg) for seg, _g in self.segments)


def worlds_from_recorder(recorder=None, limit: Optional[int] = None) -> list:
    """Harvest every replayable record from a flight recorder (default:
    the process-global RECORDER) as SimWorlds, oldest first."""
    if recorder is None:
        from kubernetes_tpu.obs.flight import RECORDER as recorder
    out = []
    for rec in recorder.records():
        if rec.capture is None or rec.kind not in ("uniform", "scan",
                                                   "fused"):
            continue
        out.append(SimWorld.from_record(rec))
        if limit is not None and len(out) >= limit:
            break
    return out


class SimResult:
    __slots__ = ("reward", "placed", "total", "packing", "spread",
                 "locality")

    def __init__(self, reward, placed, total, packing, spread, locality):
        self.reward = reward
        self.placed = placed
        self.total = total
        self.packing = packing
        self.spread = spread
        self.locality = locality

    def as_dict(self) -> dict:
        return {"reward": round(self.reward, 6), "placed": self.placed,
                "total": self.total, "packing": round(self.packing, 6),
                "spread": round(self.spread, 6),
                "locality": round(self.locality, 6)}


def _cpu_fill(ni) -> float:
    alloc = ni.allocatable.milli_cpu
    return ni.requested.milli_cpu / alloc if alloc > 0 else 0.0


def simulate(world: SimWorld, name_weights: dict,
             gang_weight: int = 0) -> SimResult:
    """Run one world under `name_weights` (reference priority names ->
    integer weights, the exact shape a SchedulingProfile row carries)
    and score the placements. Deterministic: no RNG anywhere."""
    from kubernetes_tpu.api.types import get_zone_key
    from kubernetes_tpu.factory import (
        DEFAULT_PREDICATE_NAMES, build_predicate_set,
        build_priority_configs)
    from kubernetes_tpu.obs.flight import FlightRecorder
    from kubernetes_tpu.oracle.generic_scheduler import (
        FitError, GenericScheduler, PriorityConfig)
    from kubernetes_tpu.oracle import priorities as prios

    infos = {k: ni.clone() for k, ni in world.infos.items()}
    tree = FlightRecorder._rebuild_tree(world.tree_snap)
    services = world.services
    replicasets = world.replicasets
    oracle = GenericScheduler(
        percentage_of_nodes_to_score=world.pct,
        hard_pod_affinity_weight=world.hpaw,
        nominated_pods_fn=lambda _n: [])
    oracle.last_index, oracle.last_node_index = world.li, world.lni
    cfgs = build_priority_configs(
        dict(name_weights), services_fn=lambda: services,
        replicasets_fn=lambda: replicasets,
        hard_pod_affinity_weight=world.hpaw)
    pred_names = (sorted(world.enabled) if world.enabled
                  else DEFAULT_PREDICATE_NAMES)
    t_consumed = 0

    def take_names() -> list:
        nonlocal t_consumed
        if t_consumed == 0:
            ns = list(world.names)
        elif tree is not None:
            ns = tree.list_names()
        else:
            ns = list(world.names)
        t_consumed += 1
        return ns

    def run_pod(pod, gang_zones=None):
        funcs = build_predicate_set(
            pred_names, infos, services_fn=lambda: services)
        pod_cfgs = cfgs
        if gang_weight and gang_zones is not None:
            pod_cfgs = list(cfgs) + [PriorityConfig(
                "GangLocalityPriority", gang_weight,
                function=lambda _p, nis, nodes: [
                    prios.gang_locality_map(gang_zones, nis[n.name])
                    for n in nodes])]
        try:
            r = oracle.schedule(pod, infos, take_names(),
                                predicate_funcs=funcs,
                                priority_configs=pod_cfgs)
        except FitError:
            return None
        host = r.suggested_host
        assumed = pod.clone()
        assumed.node_name = host
        ni = infos[host].clone()
        ni.add_pod(assumed)
        infos[host] = ni
        if gang_zones is not None:
            node = infos[host].node
            z = get_zone_key(node) if node is not None else ""
            if z:
                gang_zones[z] = gang_zones.get(z, 0) + 1
        return host

    placed_hosts: list = []        # (host, zone) of every placement kept
    gang_localities: list = []
    total = 0
    for seg_pods, is_gang in world.segments:
        total += len(seg_pods)
        if is_gang:
            # all-or-nothing, the kernel's contract: checkpoint, place,
            # rewind on any member's failure
            chk = (dict(infos), oracle.last_index, oracle.last_node_index,
                   t_consumed, None if tree is None else tree.checkpoint())
            gang_zones: dict = {}
            hosts = []
            failed = False
            for p in seg_pods:
                h = run_pod(p, gang_zones=gang_zones)
                if h is None:
                    failed = True
                    break
                hosts.append(h)
            if failed:
                infos = chk[0]
                oracle.last_index, oracle.last_node_index = chk[1], chk[2]
                t_consumed = chk[3]
                if tree is not None:
                    tree.restore(chk[4])
                continue
            for h in hosts:
                node = infos[h].node
                placed_hosts.append(
                    (h, get_zone_key(node) if node is not None else ""))
            if gang_zones:
                n = sum(gang_zones.values())
                gang_localities.append(max(gang_zones.values()) / n)
        else:
            for p in seg_pods:
                h = run_pod(p)
                if h is None:
                    continue
                node = infos[h].node
                placed_hosts.append(
                    (h, get_zone_key(node) if node is not None else ""))

    placed = len(placed_hosts)
    placed_frac = placed / total if total else 0.0
    used = sorted({h for h, _z in placed_hosts})
    packing = (sum(_cpu_fill(infos[h]) for h in used) / len(used)
               if used else 0.0)
    zone_counts: dict = {}
    for _h, z in placed_hosts:
        zone_counts[z] = zone_counts.get(z, 0) + 1
    if placed and len(zone_counts) > 0:
        spread = 1.0 - (max(zone_counts.values())
                        - min(zone_counts.values())) / placed
    else:
        spread = 0.0
    locality = (sum(gang_localities) / len(gang_localities)
                if gang_localities else 0.0)
    reward = (REWARD_PLACED * placed_frac + REWARD_PACK * packing
              + REWARD_SPREAD * spread + REWARD_LOCALITY * locality)
    return SimResult(reward, placed, total, packing, spread, locality)
