"""Closed-loop learned scoring — the tuner writes profile tensor rows
(round 22; ROADMAP item 3, the last item buildable without hardware).

The round-19 `[profiles x priorities]` tensor was designed so a learner
only writes ROWS: kernels, parity contracts, and the oracle referee are
untouched — a tuned row is just data, and every decision stays
bit-identical to the serial oracle given the same tensor. The loop:

    flight-recorder worlds ──> offline simulator ──> reward
             ^                        │
             │                 seeded CEM search
             │                        │ best row
    live decisions <── ProfileSet.set_row(shadow) ── shadow controller
             │                        ^
       obs/timeseries ──> promotion gate (promote / hold / demote)

- `tuner.simulator`: replays recorded flight-recorder worlds through the
  serial oracle with a CANDIDATE weight row substituted; the reward is a
  deterministic placement-quality objective (placed fraction, packing
  utilization, zone spread, gang locality). Same seed + same worlds =>
  identical reward — the search is reproducible by construction.
- `tuner.search`: seeded cross-entropy method over integer weight rows
  (bandit fallback when the world set is too thin to rank populations),
  bounded by the SAME apis/policy weight validation construction runs.
- `tuner.controller`: installs the best row as a SHADOW profile via
  `ProfileSet.set_row` (ctor-equivalent validation; serving schedulers
  refresh through `Scheduler.reload_profiles`), measures the shadow
  lane against the incumbent (fleet already partitions by claimed
  profile — the free A/B lane), and a promotion gate reads windowed
  p99 + utilization from `obs/timeseries.SeriesView`: promote (write
  the incumbent row), hold, or demote on SLO breach. NaN / no-data
  windows HOLD — the gate never promotes blind.
"""
from __future__ import annotations

from kubernetes_tpu import obs

TUNER_CANDIDATES = obs.counter(
    "tuner_candidates_evaluated_total",
    "Candidate weight rows scored by the offline simulator, by search "
    "strategy (cem | bandit).", ("strategy",))
TUNER_ROWS_WRITTEN = obs.counter(
    "tuner_rows_written_total",
    "ProfileSet.set_row writes performed by the tuner, by target row "
    "(shadow = candidate installed for A/B serving; incumbent = a "
    "promoted row).", ("row",))
TUNER_DECISIONS = obs.counter(
    "tuner_promotion_decisions_total",
    "Promotion-gate verdicts rendered, by decision "
    "(promote | hold | demote).", ("decision",))
TUNER_BEST_REWARD = obs.gauge(
    "tuner_best_reward",
    "Best simulator reward found by the most recent offline search.")
TUNER_LANE_P99 = obs.gauge(
    "tuner_lane_p99_seconds",
    "Windowed startup p99 of one serving lane (shadow vs incumbent), "
    "published by the shadow controller's observe tick; NaN when the "
    "lane committed nothing inside the window (the gate reads NaN as "
    "no-data and holds).", ("lane",))
TUNER_LANE_UTILIZATION = obs.gauge(
    "tuner_lane_utilization",
    "Mean cpu fill of the nodes hosting one lane's pods (the packing "
    "objective the reward optimizes), published by the shadow "
    "controller's observe tick; NaN when the lane hosts nothing.",
    ("lane",))

from kubernetes_tpu.tuner.simulator import (   # noqa: E402
    SimWorld, SimResult, simulate, worlds_from_recorder,
)
from kubernetes_tpu.tuner.search import (      # noqa: E402
    CEMSearch, BanditSearch, TuneResult, tune,
)
from kubernetes_tpu.tuner.controller import (  # noqa: E402
    PromotionGate, ShadowTuner, lane_series,
)

__all__ = [
    "SimWorld", "SimResult", "simulate", "worlds_from_recorder",
    "CEMSearch", "BanditSearch", "TuneResult", "tune",
    "PromotionGate", "ShadowTuner", "lane_series",
    "TUNER_CANDIDATES", "TUNER_ROWS_WRITTEN", "TUNER_DECISIONS",
    "TUNER_BEST_REWARD", "TUNER_LANE_P99", "TUNER_LANE_UTILIZATION",
]
