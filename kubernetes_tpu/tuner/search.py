"""Seeded black-box search over integer weight rows.

Two strategies, one contract — propose {priority name: int weight} rows,
score them with a caller-supplied reward, return the best:

- `CEMSearch` (the default): cross-entropy method. Each generation
  samples `population` rows from an independent per-key Gaussian,
  scores them, keeps the `elite_frac` best, and refits mean/std to the
  elites. Integer weights, clipped into [lo, hi] — and `hi` is itself
  clipped under the apis/policy MAX_WEIGHT bound, so every candidate
  the search can express passes the SAME validation ProfileSet
  construction (and set_row) runs.
- `BanditSearch` (the fallback): epsilon-greedy hill climb around the
  incumbent row — one key perturbed per step. Used when the world set
  is too thin for population ranking to mean anything (CEM elites over
  one tiny world collapse to noise), or when the evaluation budget
  can't fund a single CEM generation.

Everything is driven by one `random.Random(seed)`: same seed + same
worlds (the simulator is deterministic) => identical candidate
sequence, identical ranking, identical winner. Ties break toward the
lexicographically smallest row, so equal-reward runs are stable too.
"""
from __future__ import annotations

import random
from typing import Callable, Optional

from kubernetes_tpu.apis.policy import MAX_WEIGHT

#: default search domain: generous spread around the hand-set vectors
#: (weights are RELATIVE — the oracle sums weight * normalized score, so
#: [1, 100] spans 100:1 priority ratios, far past anything hand-tuned)
DEFAULT_LO = 1
DEFAULT_HI = 100


class TuneResult:
    __slots__ = ("best_weights", "best_reward", "evaluated", "history",
                 "strategy")

    def __init__(self, best_weights: dict, best_reward: float,
                 evaluated: int, history: list, strategy: str):
        self.best_weights = best_weights
        self.best_reward = best_reward
        self.evaluated = evaluated
        self.history = history      # per-generation (best, mean) rewards
        self.strategy = strategy

    def as_dict(self) -> dict:
        return {"best_weights": dict(self.best_weights),
                "best_reward": round(self.best_reward, 6),
                "evaluated": self.evaluated,
                "strategy": self.strategy,
                "history": [(round(b, 3), round(m, 3))
                            for b, m in self.history]}


def _row_key(w: dict) -> tuple:
    return tuple(sorted(w.items()))


class CEMSearch:
    def __init__(self, keys, seed: int = 0, population: int = 16,
                 elite_frac: float = 0.25, iterations: int = 6,
                 lo: int = DEFAULT_LO, hi: int = DEFAULT_HI,
                 init: Optional[dict] = None):
        self.keys = list(keys)
        if not self.keys:
            raise ValueError("CEMSearch needs at least one priority key")
        self.rng = random.Random(seed)
        self.population = max(4, int(population))
        self.n_elite = max(2, int(self.population * elite_frac))
        self.iterations = max(1, int(iterations))
        self.lo = max(1, int(lo))                     # policy: positive
        self.hi = min(int(hi), MAX_WEIGHT - 1)        # policy: < MAX_WEIGHT
        span = self.hi - self.lo
        init = init or {}
        self.mu = {k: float(init.get(k, (self.lo + self.hi) / 2))
                   for k in self.keys}
        self.sigma = {k: max(1.0, span / 4) for k in self.keys}

    def _sample(self) -> dict:
        return {k: int(min(self.hi, max(
            self.lo, round(self.rng.gauss(self.mu[k], self.sigma[k])))))
            for k in self.keys}

    def run(self, score_fn: Callable[[dict], float]) -> TuneResult:
        from kubernetes_tpu.tuner import TUNER_CANDIDATES
        best_w: Optional[dict] = None
        best_r = float("-inf")
        evaluated = 0
        history = []
        for _gen in range(self.iterations):
            pop = [self._sample() for _ in range(self.population)]
            scored = [(score_fn(w), _row_key(w), w) for w in pop]
            evaluated += len(scored)
            TUNER_CANDIDATES.labels("cem").inc(len(scored))
            # reward desc, then row asc: equal rewards rank stably
            scored.sort(key=lambda t: (-t[0], t[1]))
            elites = scored[:self.n_elite]
            if elites[0][0] > best_r or (
                    elites[0][0] == best_r and best_w is not None
                    and elites[0][1] < _row_key(best_w)):
                best_r, best_w = elites[0][0], dict(elites[0][2])
            history.append((elites[0][0],
                            sum(s for s, _k, _w in scored) / len(scored)))
            for k in self.keys:
                vals = [w[k] for _s, _kk, w in elites]
                mean = sum(vals) / len(vals)
                var = sum((v - mean) ** 2 for v in vals) / len(vals)
                self.mu[k] = mean
                # a variance floor keeps late generations exploring one
                # step either way instead of freezing on the first elite
                self.sigma[k] = max(1.0, var ** 0.5)
        return TuneResult(best_w or {}, best_r, evaluated, history, "cem")


class BanditSearch:
    """Epsilon-greedy hill climb around an incumbent row."""

    def __init__(self, keys, seed: int = 0, steps: int = 32,
                 epsilon: float = 0.2, lo: int = DEFAULT_LO,
                 hi: int = DEFAULT_HI, init: Optional[dict] = None):
        self.keys = list(keys)
        if not self.keys:
            raise ValueError("BanditSearch needs at least one priority key")
        self.rng = random.Random(seed)
        self.steps = max(1, int(steps))
        self.epsilon = float(epsilon)
        self.lo = max(1, int(lo))
        self.hi = min(int(hi), MAX_WEIGHT - 1)
        init = init or {}
        self.current = {k: int(min(self.hi, max(self.lo, init.get(k, 1))))
                        for k in self.keys}

    def _neighbor(self, w: dict) -> dict:
        out = dict(w)
        k = self.rng.choice(self.keys)
        if self.rng.random() < self.epsilon:
            out[k] = self.rng.randint(self.lo, self.hi)   # explore: jump
        else:
            step = self.rng.choice((-4, -2, -1, 1, 2, 4))
            out[k] = int(min(self.hi, max(self.lo, out[k] + step)))
        return out

    def run(self, score_fn: Callable[[dict], float]) -> TuneResult:
        from kubernetes_tpu.tuner import TUNER_CANDIDATES
        best_w = dict(self.current)
        best_r = score_fn(best_w)
        evaluated = 1
        history = [(best_r, best_r)]
        for _ in range(self.steps):
            cand = self._neighbor(best_w)
            r = score_fn(cand)
            evaluated += 1
            TUNER_CANDIDATES.labels("bandit").inc()
            if r > best_r or (r == best_r
                              and _row_key(cand) < _row_key(best_w)):
                best_r, best_w = r, cand
            history.append((best_r, r))
        return TuneResult(best_w, best_r, evaluated, history, "bandit")


def tune(worlds: list, keys, seed: int = 0,
         incumbent: Optional[dict] = None, budget: int = 96,
         gang_weight: int = 0, lo: int = DEFAULT_LO,
         hi: int = DEFAULT_HI, min_worlds_for_cem: int = 2) -> TuneResult:
    """The offline search entrypoint: score = summed simulator reward
    over `worlds`. CEM when the world set and budget can fund population
    ranking; the bandit fallback otherwise. Deterministic for a given
    (worlds, keys, seed, budget)."""
    from kubernetes_tpu.tuner import TUNER_BEST_REWARD
    from kubernetes_tpu.tuner.simulator import simulate

    def score(w: dict) -> float:
        return sum(simulate(world, w, gang_weight=gang_weight).reward
                   for world in worlds)

    population = 16
    use_cem = (len(worlds) >= min_worlds_for_cem
               and budget >= 2 * population)
    if use_cem:
        iters = max(1, budget // population)
        res = CEMSearch(keys, seed=seed, population=population,
                        iterations=iters, lo=lo, hi=hi,
                        init=incumbent).run(score)
    else:
        res = BanditSearch(keys, seed=seed, steps=max(1, budget - 1),
                           lo=lo, hi=hi, init=incumbent).run(score)
    TUNER_BEST_REWARD.set(res.best_reward)
    return res
