"""Shadow-profile controller + promotion gate (the online half).

The offline search ends with a candidate row; serving it is a
`ProfileSet.set_row` write into the SHADOW profile — the fleet already
partitions responsibility by claimed profile (round 18), so exactly one
instance serves the candidate and the cluster runs a live A/B split
with zero new serving machinery. `ShadowTuner` owns the writes (and the
`Scheduler.reload_profiles` refresh that makes them live), publishes the
per-lane measurement gauges each observe tick, and applies the gate's
verdicts.

`PromotionGate` reads the evidence the way the soak verdict engine does
(round 21): a `SeriesView` over the timeseries scraper's document, lane
columns `tuner_lane_p99_seconds{lane}` / `tuner_lane_utilization{lane}`,
judged over the trailing `tail` fraction of the observation window.

The asymmetry is deliberate and load-bearing:
- PROMOTE requires positive evidence: enough valid samples in BOTH
  lanes, the shadow beating the incumbent on p99 and/or utilization,
  and no regression past tolerance on the other axis.
- HOLD is the default: NaN columns, missing families, and thin windows
  all hold. No data NEVER promotes.
- DEMOTE fires on an SLO breach of the shadow lane alone — a bad row is
  pulled without waiting for a full comparison window.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from kubernetes_tpu.obs.timeseries import SeriesView

#: default shadow observation window knobs
DEFAULT_SLO_SECONDS = 5.0
DEFAULT_MIN_SAMPLES = 4
DEFAULT_TAIL = 0.5


def lane_series(view, family: str, lane: str,
                col: str = "value") -> np.ndarray:
    """One lane's column from a series document — the per-child twin of
    SeriesView.col (which SUMS children and would blend the lanes)."""
    if not isinstance(view, SeriesView):
        view = SeriesView(view)
    n = len(view.t)
    fam = view.doc.get("families", {}).get(family)
    if fam is None:
        return np.full(n, np.nan)
    ser = fam["series"].get(f'lane="{lane}"')
    if ser is None:
        return np.full(n, np.nan)
    vals = ser.get(col)
    if vals is None:
        return np.full(n, np.nan)
    return np.asarray([np.nan if v is None else float(v) for v in vals],
                      dtype=np.float64)


class PromotionGate:
    """Promote / hold / demote over the shadow lane's evidence window."""

    def __init__(self, slo: float = DEFAULT_SLO_SECONDS,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 p99_tolerance: float = 0.10,
                 util_tolerance: float = 0.05,
                 tail: float = DEFAULT_TAIL):
        self.slo = float(slo)
        self.min_samples = int(min_samples)
        self.p99_tolerance = float(p99_tolerance)
        self.util_tolerance = float(util_tolerance)
        self.tail = float(tail)

    def decide(self, view_or_doc) -> dict:
        """Render one verdict from a timeseries document (or SeriesView).
        Returns {"decision": promote|hold|demote, "reason", "stats"}."""
        from kubernetes_tpu.tuner import TUNER_DECISIONS
        view = (view_or_doc if isinstance(view_or_doc, SeriesView)
                else SeriesView(view_or_doc))
        lo = 1.0 - self.tail
        stats: dict = {}
        cols: dict = {}
        for lane in ("incumbent", "shadow"):
            p99 = lane_series(view, "tuner_lane_p99_seconds", lane)
            util = lane_series(view, "tuner_lane_utilization", lane)
            cols[lane] = (p99, util)
            stats[lane] = {
                "p99": view.seg_mean(p99, lo, 1.0),
                "utilization": view.seg_mean(util, lo, 1.0),
                "p99_samples": view.valid(p99),
                "util_samples": view.valid(util),
            }

        def verdict(decision: str, reason: str) -> dict:
            TUNER_DECISIONS.labels(decision).inc()
            return {"decision": decision, "reason": reason,
                    "stats": {l: {k: (None if isinstance(v, float)
                                      and np.isnan(v) else
                                      (round(v, 6) if isinstance(v, float)
                                       else v))
                                  for k, v in s.items()}
                              for l, s in stats.items()}}

        sh_p99 = stats["shadow"]["p99"]
        # demote needs only the shadow's own evidence: a breaching row
        # is pulled even while the incumbent lane is still dark
        if stats["shadow"]["p99_samples"] >= self.min_samples \
                and not np.isnan(sh_p99) and sh_p99 > self.slo:
            return verdict(
                "demote", f"shadow windowed p99 {sh_p99:.3f}s breaches "
                          f"the {self.slo:.1f}s SLO")
        for lane in ("incumbent", "shadow"):
            s = stats[lane]
            if s["p99_samples"] < self.min_samples \
                    or s["util_samples"] < self.min_samples:
                return verdict("hold", f"{lane} lane has insufficient "
                                       f"valid samples (no-data holds, "
                                       f"never promotes)")
            if np.isnan(s["p99"]) or np.isnan(s["utilization"]):
                return verdict("hold", f"{lane} lane window is NaN "
                                       f"(no-data holds, never promotes)")
        in_p99 = stats["incumbent"]["p99"]
        sh_u = stats["shadow"]["utilization"]
        in_u = stats["incumbent"]["utilization"]
        p99_ok = sh_p99 <= in_p99 * (1.0 + self.p99_tolerance) \
            or sh_p99 <= self.slo * 0.1
        util_ok = sh_u >= in_u * (1.0 - self.util_tolerance)
        wins = (sh_p99 < in_p99) or (sh_u > in_u)
        if p99_ok and util_ok and wins:
            return verdict(
                "promote",
                f"shadow wins (p99 {sh_p99:.3f}s vs {in_p99:.3f}s, "
                f"utilization {sh_u:.3f} vs {in_u:.3f}) without "
                f"regression past tolerance")
        return verdict("hold", "shadow does not beat the incumbent on "
                               "p99 or utilization yet")


def prefix_lanes(incumbent_prefix: str,
                 shadow_prefix: str) -> dict:
    """Lane predicates over ledger pod keys ("namespace/name"): the
    harness names each lane's pods with a distinct prefix."""
    def match(prefix: str) -> Callable[[str], bool]:
        return lambda key: key.split("/", 1)[-1].startswith(prefix)
    return {"incumbent": match(incumbent_prefix),
            "shadow": match(shadow_prefix)}


def lane_utilization(node_infos, match: Callable[[str], bool]) -> float:
    """Mean cpu fill of the nodes hosting >= 1 pod the lane predicate
    claims — the packing objective, measured on the LIVE cluster. NaN
    when the lane hosts nothing (no-data, not zero)."""
    fills = []
    for ni in (node_infos.values() if hasattr(node_infos, "values")
               else node_infos):
        if ni.node is None or not ni.pods:
            continue
        if any(match(p.key) for p in ni.pods):
            alloc = ni.allocatable.milli_cpu
            fills.append(ni.requested.milli_cpu / alloc
                         if alloc > 0 else 0.0)
    return sum(fills) / len(fills) if fills else float("nan")


class ShadowTuner:
    """Owns the shadow row: install, measure, and apply gate verdicts."""

    def __init__(self, profiles, shadow: str,
                 incumbent: Optional[str] = None,
                 schedulers=(), lane_match: Optional[dict] = None,
                 window: Optional[float] = None, ledger=None):
        self.profiles = profiles
        self.shadow = shadow
        self.incumbent = (incumbent if incumbent is not None
                          else profiles.default.name)
        if profiles.index_of(shadow) is None:
            raise ValueError(f"shadow profile {shadow!r} not in the set")
        if profiles.index_of(self.incumbent) is None:
            raise ValueError(
                f"incumbent profile {self.incumbent!r} not in the set")
        self.schedulers = list(schedulers)
        self.lane_match = lane_match or prefix_lanes("tn-i-", "tn-s-")
        self.window = window
        if ledger is None:
            from kubernetes_tpu.obs.ledger import LEDGER as ledger
        self.ledger = ledger
        self.last_decision: Optional[dict] = None
        self.installed: Optional[dict] = None
        self._register_debug()

    # -- writes --------------------------------------------------------------
    def _refresh(self) -> None:
        """Make a row write LIVE on every serving scheduler (oracle
        config lists + the device weight tensor)."""
        for s in self.schedulers:
            reload = getattr(s, "reload_profiles", None)
            if reload is None:           # a FleetInstance: unwrap
                s.sched.reload_profiles()
            else:
                reload()

    def install(self, weights: dict):
        """Write the candidate into the SHADOW row (ctor-equivalent
        validation inside set_row; nothing mutates on failure)."""
        from kubernetes_tpu.tuner import TUNER_ROWS_WRITTEN
        prof = self.profiles.set_row(self.shadow, dict(weights))
        self.installed = dict(weights)
        TUNER_ROWS_WRITTEN.labels("shadow").inc()
        self._refresh()
        return prof

    def promote(self):
        """Write the shadow's row into the INCUMBENT row."""
        from kubernetes_tpu.tuner import TUNER_ROWS_WRITTEN
        shadow = self.profiles.profile_for(self.shadow)
        prof = self.profiles.set_row(
            self.incumbent, shadow.name_weights(),
            rank_aware=shadow.rank_aware, gang_weight=shadow.gang_weight)
        TUNER_ROWS_WRITTEN.labels("incumbent").inc()
        self._refresh()
        return prof

    def demote(self):
        """Pull the experiment: the shadow row reverts to the incumbent's
        weights (the lane keeps serving, just not the candidate)."""
        from kubernetes_tpu.tuner import TUNER_ROWS_WRITTEN
        inc = self.profiles.profile_for(self.incumbent)
        prof = self.profiles.set_row(
            self.shadow, inc.name_weights(),
            rank_aware=inc.rank_aware, gang_weight=inc.gang_weight)
        self.installed = None
        TUNER_ROWS_WRITTEN.labels("shadow").inc()
        self._refresh()
        return prof

    def apply(self, decision: dict) -> dict:
        """Apply a gate verdict (promote/demote write rows; hold is a
        no-op). Returns the decision for chaining."""
        self.last_decision = decision
        d = decision.get("decision")
        if d == "promote":
            self.promote()
        elif d == "demote":
            self.demote()
        return decision

    # -- measurement ---------------------------------------------------------
    def observe(self, node_infos, now: Optional[float] = None) -> dict:
        """One measurement tick: publish each lane's windowed p99 (ledger,
        per-lane key filter) and live packing utilization to the
        `tuner_lane_*` gauges — the scraper samples them into the series
        the gate reads. NaN = the lane produced nothing this window."""
        from kubernetes_tpu.tuner import (
            TUNER_LANE_P99, TUNER_LANE_UTILIZATION)
        out = {}
        for lane, match in self.lane_match.items():
            n = self.ledger.window_count(self.window, now, match)
            p99 = (self.ledger.window_percentile(
                0.99, self.window, now, match) if n else float("nan"))
            util = lane_utilization(node_infos, match)
            TUNER_LANE_P99.labels(lane).set(p99)
            TUNER_LANE_UTILIZATION.labels(lane).set(util)
            out[lane] = {"p99": p99, "utilization": util, "committed": n}
        return out

    # -- /debug/sched --------------------------------------------------------
    def _register_debug(self) -> None:
        import weakref
        from kubernetes_tpu import obs
        ref = weakref.ref(self)

        def snap():
            t = ref()
            return None if t is None else t.debug_state()
        obs.register_debug("tuner", snap)

    def debug_state(self) -> dict:
        shadow = self.profiles.profile_for(self.shadow)
        inc = self.profiles.profile_for(self.incumbent)
        return {
            "shadow": self.shadow,
            "incumbent": self.incumbent,
            "profile_version": self.profiles.version,
            "installed": self.installed,
            "shadow_weights": dict(shadow.name_weights()),
            "incumbent_weights": dict(inc.name_weights()),
            "last_decision": self.last_decision,
        }
