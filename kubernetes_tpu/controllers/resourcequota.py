"""ResourceQuota controller — pkg/controller/resourcequota.

Reconciles each quota's `used` totals (aggregate pod cpu/memory requests +
pod count per namespace) from live state. The admission plugin both
enforces `hard` AND commits usage synchronously on create (CAS); this
controller reconciles the drift admission can't see — deletes, terminal
phases (the reference's quota evaluator scopes to non-terminal pods)."""
from __future__ import annotations

from kubernetes_tpu.api.types import Pod, ResourceQuota, get_resource_request
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.store import (
    Store, PODS, RESOURCEQUOTAS, NotFoundError,
)

TERMINAL_PHASES = ("Succeeded", "Failed")


def pod_usage(pod: Pod) -> dict[str, int]:
    req = get_resource_request(pod)
    return {"cpu": req.milli_cpu, "memory": req.memory, "pods": 1}


class ResourceQuotaController:
    def __init__(self, store: Store):
        self.store = store
        self.informers = InformerFactory(store)
        self._dirty: set[str] = set()
        quotas = self.informers.informer(RESOURCEQUOTAS)
        quotas.add_event_handler(
            on_add=lambda q: self._dirty.add(q.key),
            on_update=lambda o, n: self._dirty.add(n.key),
            on_delete=lambda q: self._dirty.discard(q.key))
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=self._pod_changed,
                               on_update=lambda o, n: self._pod_changed(n),
                               on_delete=self._pod_changed)

    def _pod_changed(self, pod: Pod) -> None:
        for q in self.informers.informer(RESOURCEQUOTAS).list():
            if q.namespace == pod.namespace:
                self._dirty.add(q.key)

    def sync(self) -> None:
        self.informers.sync_all()
        for q in self.informers.informer(RESOURCEQUOTAS).list():
            self._dirty.add(q.key)
        self.reconcile_dirty()

    def pump(self) -> int:
        self.informers.pump_all()
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty:
            key = self._dirty.pop()
            try:
                quota = self.store.get(RESOURCEQUOTAS, key)
            except NotFoundError:
                continue
            self.reconcile(quota)
            n += 1
        return n

    def reconcile(self, quota: ResourceQuota) -> None:
        # the pod total is computed INSIDE the CAS mutate so a retry after a
        # concurrent admission charge (admission.py commits usage on admit)
        # re-lists live pods instead of clobbering the quota with a stale
        # pre-charge total
        def mutate(cur):
            pods, _rv = self.store.list(PODS)
            used = {k: 0 for k in cur.hard}
            for p in pods:
                if p.namespace != cur.namespace or p.deleted \
                        or p.phase in TERMINAL_PHASES:
                    continue
                for k, v in pod_usage(p).items():
                    if k in used:
                        used[k] += v
            if used == cur.used:
                return None
            cur.used = used
            return cur
        try:
            self.store.guaranteed_update(RESOURCEQUOTAS, quota.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass
