"""CronJob controller — pkg/controller/cronjob/cronjob_controller.go.

syncOne semantics: for every CronJob, find the unmet schedule times since
the last run (getRecentUnmetScheduleTimes), start a Job for the most
recent one, and apply the concurrency policy against still-active owned
Jobs (Allow runs them side by side, Forbid skips the new run, Replace
deletes the active ones first). Too many missed runs (>100) emits the
reference's warning and resets the cursor; the optional starting deadline
drops runs that are already stale.

Schedules are evaluated in **UTC** (utils.cron.CronSchedule), a deliberate
divergence from the reference controller-manager's local-time evaluation:
firing times here never depend on the host's timezone."""
from __future__ import annotations

import time as _time
from typing import Optional

from kubernetes_tpu.api.types import CronJob, Job
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL, WARNING
from kubernetes_tpu.store.store import (
    Store, CRONJOBS, JOBS, AlreadyExistsError, NotFoundError,
)
from kubernetes_tpu.utils.cron import CronSchedule, CronParseError

MAX_MISSED = 100          # cronjob_controller.go:~"Too many missed times"


class CronJobController(DirtyKeyController):
    KIND = CRONJOBS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        self.recorder = EventRecorder(store, component="cronjob-controller")
        # (schedule expr, cursor) -> next fire time, so the steady-state
        # resync is O(1) per CronJob instead of a minute-scan per pump
        self._next: dict[str, tuple[str, float, Optional[float]]] = {}

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else _time.time()

    def pump(self) -> int:
        # time moves even when no event does: one resync pass covers every
        # schedule (the reference's 10s resync) — event-dirtied keys ride
        # the same drain instead of reconciling twice
        self.informers.pump_all()
        for cj in self.informers.informer(CRONJOBS).list():
            self._dirty.add(cj.key)
        return self.reconcile_dirty()

    def _active_owned_jobs(self, cj: CronJob) -> list[Job]:
        return [j for j in self.store.list(JOBS)[0]
                if j.namespace == cj.namespace
                and j.owner_ref is not None
                and j.owner_ref[:2] == ("CronJob", cj.name)
                and not j.complete and not j.job_failed]

    def reconcile(self, cj: CronJob) -> None:
        if cj.suspend or cj.template is None:
            return
        try:
            sched = CronSchedule(cj.schedule)
        except CronParseError as e:
            self.recorder.event("CronJob", cj.key, WARNING,
                                "InvalidSchedule", str(e))
            return
        now = self._now()
        start = cj.last_schedule_time
        if start is None:
            # first sight: start the clock now — the first run fires at the
            # next matching minute (the reference anchors on creation time)
            self._set_cursor(cj, now)
            return
        cached = self._next.get(cj.key)
        if cached is not None and cached[0] == cj.schedule \
                and cached[1] == start:
            nxt = cached[2]
            if nxt is None or nxt > now:
                return   # nothing due yet: skip the minute scan entirely
        # unmet times in (start, now]
        unmet = []
        t = sched.next_after(start)
        if t is None or t > now:
            self._next[cj.key] = (cj.schedule, start, t)
            return
        while t is not None and t <= now:
            unmet.append(t)
            if len(unmet) > MAX_MISSED:
                self.recorder.event(
                    "CronJob", cj.key, WARNING, "TooManyMissedTimes",
                    f"too many missed start times (> {MAX_MISSED}); "
                    "check clock skew")
                self._set_cursor(cj, now)
                return
            t = sched.next_after(t)
        if not unmet:
            return
        run_time = unmet[-1]   # only the most recent unmet time runs
        if cj.starting_deadline_seconds is not None and \
                now - run_time > cj.starting_deadline_seconds:
            self.recorder.event("CronJob", cj.key, WARNING, "MissSchedule",
                                "missed starting deadline for run")
            self._set_cursor(cj, run_time)
            return
        active = self._active_owned_jobs(cj)
        if active:
            if cj.concurrency_policy == "Forbid":
                self.recorder.event(
                    "CronJob", cj.key, NORMAL, "JobAlreadyActive",
                    "skipping run: previous Job still active")
                self._set_cursor(cj, run_time)
                return
            if cj.concurrency_policy == "Replace":
                for j in active:
                    try:
                        self.store.delete(JOBS, j.key)
                    except NotFoundError:
                        pass
        job = Job(
            name=f"{cj.name}-{int(run_time // 60)}",   # minute-stamped name
            namespace=cj.namespace,
            template=cj.template,
            completions=cj.completions,
            parallelism=cj.parallelism,
            owner_ref=("CronJob", cj.name, ""))
        try:
            self.store.create(JOBS, job)
            self.recorder.event("CronJob", cj.key, NORMAL, "SuccessfulCreate",
                                f"Created job {job.name}")
        except AlreadyExistsError:
            pass   # this tick already ran (controller restart replay)
        self._set_cursor(cj, run_time)

    def _set_cursor(self, cj: CronJob, t: float) -> None:
        def mutate(cur):
            if cur.last_schedule_time is not None \
                    and cur.last_schedule_time >= t:
                return None
            cur.last_schedule_time = t
            return cur
        try:
            self.store.guaranteed_update(CRONJOBS, cj.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass
