"""StatefulSet controller — pkg/controller/statefulset/stateful_set.go.

Stable ordinal identities: pods are named `{set}-0` .. `{set}-{N-1}` and
reconciled IN ORDER. OrderedReady (the default) creates ordinal i only when
every lower ordinal exists and is Running, and scales down from the highest
ordinal one at a time; Parallel creates/deletes without waiting
(reference: pkg/apis/apps/types.go PodManagementPolicyType).
"""
from __future__ import annotations

from kubernetes_tpu.api.types import Pod, StatefulSet
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, STATEFULSETS, AlreadyExistsError, NotFoundError,
)


class StatefulSetController(DirtyKeyController):
    KIND = STATEFULSETS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        from kubernetes_tpu.apiserver.admission import AdmissionChain
        self.admission = AdmissionChain()
        self.recorder = EventRecorder(store, component="controllermanager")

    def _register_extra_handlers(self) -> None:
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=self._pod_changed,
                               on_update=lambda o, n: self._pod_changed(n),
                               on_delete=self._pod_changed)

    def _pod_changed(self, pod: Pod) -> None:
        if pod.owner_ref is not None and pod.owner_ref[0] == "StatefulSet":
            self._dirty.add(f"{pod.namespace}/{pod.owner_ref[1]}")

    # -- syncStatefulSet -----------------------------------------------------
    def _ordinal_pods(self, sts: StatefulSet) -> dict[int, Pod]:
        pods, _rv = self.store.list(PODS)
        out: dict[int, Pod] = {}
        prefix = f"{sts.name}-"
        for p in pods:
            if p.namespace != sts.namespace or p.deleted:
                continue
            if p.owner_ref is None \
                    or p.owner_ref[:2] != ("StatefulSet", sts.name):
                continue
            tail = p.name[len(prefix):] if p.name.startswith(prefix) else ""
            if tail.isdigit():
                out[int(tail)] = p
        return out

    def reconcile(self, sts: StatefulSet) -> None:
        have = self._ordinal_pods(sts)
        ordered = sts.pod_management_policy != "Parallel"
        from kubernetes_tpu.apiserver.admission import AdmissionError
        from kubernetes_tpu.api.types import PodTemplate
        tmpl = sts.template or PodTemplate()
        # scale up: ordinals 0..replicas-1, each gated on its predecessor
        # being Running under OrderedReady
        for i in range(sts.replicas):
            if i in have:
                if ordered and have[i].phase != "Running":
                    break   # wait for this ordinal before touching later ones
                continue
            pod = tmpl.make_pod(
                f"{sts.name}-{i}", sts.namespace,
                owner_ref=("StatefulSet", sts.name, f"sts-{sts.name}"),
                extra_labels={"statefulset.kubernetes.io/pod-name":
                              f"{sts.name}-{i}"})
            admitted = None
            try:
                pod = admitted = self.admission.admit(PODS, pod, self.store)
                self.store.create(PODS, pod)
                self.recorder.event(
                    "StatefulSet", sts.key, NORMAL, "SuccessfulCreate",
                    f"create Pod {pod.name} in StatefulSet {sts.name} "
                    "successful")
            except AlreadyExistsError:
                self.admission.refund(PODS, admitted, self.store)
            except AdmissionError as e:
                self.recorder.event(
                    "StatefulSet", sts.key, "Warning", "FailedCreate",
                    f"Error creating: {e}")
                break
            if ordered:
                break   # one ordinal per pass; wait for it to come up
        # scale down: highest ordinal first, one at a time under OrderedReady
        over = sorted((i for i in have if i >= sts.replicas), reverse=True)
        for i in over:
            try:
                self.store.delete(PODS, have[i].key)
                self.recorder.event(
                    "StatefulSet", sts.key, NORMAL, "SuccessfulDelete",
                    f"delete Pod {have[i].name} in StatefulSet {sts.name} "
                    "successful")
            except NotFoundError:
                pass
            if ordered:
                break
        self._update_status(sts)

    def _update_status(self, sts: StatefulSet) -> None:
        have = self._ordinal_pods(sts)
        current = len(have)
        ready = sum(1 for p in have.values() if p.phase == "Running")

        def mutate(cur):
            if cur.current_replicas == current and cur.ready_replicas == ready:
                return None
            cur.current_replicas = current
            cur.ready_replicas = ready
            return cur
        try:
            self.store.guaranteed_update(STATEFULSETS, sts.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass
