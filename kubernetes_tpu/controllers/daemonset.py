"""DaemonSet controller — pkg/controller/daemon/daemon_controller.go:81.

One pod per eligible node. In this reference snapshot the DS controller
schedules its own pods — it sets nodeName directly instead of leaving pods
Pending for the scheduler (ScheduleDaemonSetPods was still feature-gated
off by default) — mirrored here: eligibility is the template's node
selector plus NoSchedule/NoExecute taint toleration against the node
(daemon_controller.go nodeShouldRunDaemonPod), and placement bypasses the
scheduling queue entirely.
"""
from __future__ import annotations

from kubernetes_tpu.api.types import (
    DaemonSet, Node, Pod, find_intolerable_taint, NO_SCHEDULE, NO_EXECUTE,
)
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, NODES, DAEMONSETS, AlreadyExistsError, NotFoundError,
)


class DaemonSetController(DirtyKeyController):
    KIND = DAEMONSETS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        from kubernetes_tpu.apiserver.admission import AdmissionChain
        self.admission = AdmissionChain()
        self.recorder = EventRecorder(store, component="controllermanager")

    def _register_extra_handlers(self) -> None:
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=self._pod_changed,
                               on_update=lambda o, n: self._pod_changed(n),
                               on_delete=self._pod_changed)
        nodes = self.informers.informer(NODES)
        # eligibility reads labels + taints only; other node churn
        # (heartbeats, conditions) must not trigger full reconciles
        nodes.add_event_handler(
            on_add=self._node_changed,
            on_update=lambda o, n: ((o.labels != n.labels
                                     or o.taints != n.taints)
                                    and self._node_changed(n)),
            on_delete=self._node_changed)

    def _pod_changed(self, pod: Pod) -> None:
        if pod.owner_ref is not None and pod.owner_ref[0] == "DaemonSet":
            self._dirty.add(f"{pod.namespace}/{pod.owner_ref[1]}")

    def _node_changed(self, _node: Node) -> None:
        for d in self.informers.informer(DAEMONSETS).list():
            self._dirty.add(d.key)

    # -- nodeShouldRunDaemonPod ----------------------------------------------
    def _eligible(self, ds: DaemonSet, node: Node) -> bool:
        tmpl = ds.template
        if tmpl is not None and tmpl.node_selector:
            if any(node.labels.get(k) != v
                   for k, v in tmpl.node_selector.items()):
                return False
        tols = tmpl.tolerations if tmpl is not None else ()
        bad = find_intolerable_taint(
            node.taints, tols,
            lambda t: t.effect in (NO_SCHEDULE, NO_EXECUTE))
        return bad is None

    def reconcile(self, ds: DaemonSet) -> None:
        nodes, _rv = self.store.list(NODES)
        pods, _rv = self.store.list(PODS)
        mine = [p for p in pods
                if p.namespace == ds.namespace and not p.deleted
                and p.owner_ref is not None
                and p.owner_ref[:2] == ("DaemonSet", ds.name)]
        by_node: dict[str, list[Pod]] = {}
        for p in mine:
            by_node.setdefault(p.node_name, []).append(p)
        eligible = {n.name for n in nodes if self._eligible(ds, n)}

        from kubernetes_tpu.apiserver.admission import AdmissionError
        for name in sorted(eligible):
            have = by_node.get(name, [])
            if not have:
                # the DS controller schedules: nodeName set at create
                from kubernetes_tpu.api.types import PodTemplate
                tmpl = ds.template or PodTemplate()
                pod = tmpl.make_pod(
                    f"{ds.name}-{name}", ds.namespace,
                    owner_ref=("DaemonSet", ds.name, f"ds-{ds.name}"),
                    node_name=name)
                admitted = None
                try:
                    pod = admitted = self.admission.admit(PODS, pod, self.store)
                    self.store.create(PODS, pod)
                except AlreadyExistsError:
                    self.admission.refund(PODS, admitted, self.store)
                except AdmissionError as e:
                    self.recorder.event(
                        "DaemonSet", ds.key, "Warning", "FailedCreate",
                        f"Error creating: {e}")
                    break
            elif len(have) > 1:
                # duplicate daemons on one node: keep the oldest
                for p in sorted(have, key=lambda p: p.creation_timestamp)[1:]:
                    try:
                        self.store.delete(PODS, p.key)
                    except NotFoundError:
                        pass
        # pods on nodes that are gone or no longer eligible are evicted
        for name, have in by_node.items():
            if name not in eligible:
                for p in have:
                    try:
                        self.store.delete(PODS, p.key)
                        self.recorder.event(
                            "DaemonSet", ds.key, NORMAL, "SuccessfulDelete",
                            f"Deleted pod {p.name} (node ineligible)")
                    except NotFoundError:
                        pass
        self._update_status(ds, len(eligible))

    def _update_status(self, ds: DaemonSet, desired: int) -> None:
        pods, _rv = self.store.list(PODS)
        mine = [p for p in pods
                if p.namespace == ds.namespace and not p.deleted
                and p.owner_ref is not None
                and p.owner_ref[:2] == ("DaemonSet", ds.name)]
        current = len({p.node_name for p in mine if p.node_name})
        ready = sum(1 for p in mine if p.phase == "Running")

        def mutate(cur):
            if (cur.desired_number_scheduled == desired
                    and cur.current_number_scheduled == current
                    and cur.number_ready == ready):
                return None
            cur.desired_number_scheduled = desired
            cur.current_number_scheduled = current
            cur.number_ready = ready
            return cur
        try:
            self.store.guaranteed_update(DAEMONSETS, ds.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass
