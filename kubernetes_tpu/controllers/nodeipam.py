"""Node IPAM controller — pkg/controller/nodeipam (range allocator).

Splits the cluster CIDR into fixed-size per-node subnets and assigns one
to every node missing spec.podCIDR (the RangeAllocator's in-memory bitmap
rebuilt from the live node set on every pass, so restarts and node
deletions release slots for free)."""
from __future__ import annotations

import ipaddress

from kubernetes_tpu.api.types import Node
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, WARNING
from kubernetes_tpu.store.store import Store, NODES, NotFoundError

DEFAULT_CLUSTER_CIDR = "10.0.0.0/16"
DEFAULT_NODE_MASK = 24


class NodeIpamController(DirtyKeyController):
    KIND = NODES

    def __init__(self, store: Store, clock=None,
                 cluster_cidr: str = DEFAULT_CLUSTER_CIDR,
                 node_mask: int = DEFAULT_NODE_MASK):
        super().__init__(store, clock=clock)
        net = ipaddress.ip_network(cluster_cidr)
        self._subnets = [str(s) for s in net.subnets(
            new_prefix=node_mask)]
        self._used: set[str] = set()
        self.recorder = EventRecorder(store, component="node-ipam")

    def reconcile_dirty(self) -> int:
        # ONE store list per drain (the informer cache lags mid-drain
        # assignments); reconcile() keeps the set current incrementally —
        # the per-node store.list would be O(N^2) clones on a full sync
        self._used = {n.pod_cidr for n in self.store.list(NODES)[0]
                      if n.pod_cidr}
        return super().reconcile_dirty()

    def reconcile(self, node: Node) -> None:
        if node.pod_cidr:
            return
        cidr = next((s for s in self._subnets if s not in self._used), None)
        if cidr is None:
            # range exhausted (reference: CIDRNotAvailable event)
            self.recorder.event("Node", node.key, WARNING,
                                "CIDRNotAvailable",
                                "no remaining pod CIDRs in the cluster "
                                "range")
            return

        def mutate(cur, _cidr=cidr):
            if cur.pod_cidr:
                return None
            cur.pod_cidr = _cidr
            return cur
        try:
            updated = self.store.guaranteed_update(NODES, node.key, mutate,
                                                   allow_skip=True)
        except NotFoundError:
            return
        self._used.add(updated.pod_cidr or cidr)
