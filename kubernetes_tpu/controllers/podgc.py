"""Pod garbage collector — pkg/controller/podgc/gc_controller.go.

Three sweeps per reconcile (gc_controller.go:gc):
- gcTerminated: when a terminated-pod threshold is configured, delete the
  oldest Succeeded/Failed pods beyond it (sorted by creation time).
- gcOrphaned: pods bound to a node that no longer exists are deleted.
- gcUnscheduledTerminating: terminating pods never scheduled to a node are
  force-deleted.
"""
from __future__ import annotations

from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import Store, PODS, NODES, NotFoundError

TERMINATED_PHASES = ("Succeeded", "Failed")


class PodGCController:
    def __init__(self, store: Store, terminated_pod_threshold: int = 0):
        self.store = store
        self.threshold = terminated_pod_threshold   # 0 = sweep disabled
        self.recorder = EventRecorder(store, component="controllermanager")
        self.informers = InformerFactory(store)

    def sync(self) -> None:
        self.informers.sync_all()
        self.gc()

    def pump(self) -> int:
        self.informers.pump_all()
        return self.gc()

    def _delete(self, pod, reason: str, event: str = "PodGC") -> bool:
        try:
            self.store.delete(PODS, pod.key)
        except NotFoundError:
            return False
        self.recorder.pod_event(pod, NORMAL, event,
                                f"{reason}: deleting pod {pod.key}")
        return True

    def gc(self) -> int:
        pods, _rv = self.store.list(PODS)
        nodes = {n.name for n in self.store.list(NODES)[0]}
        deleted = 0
        # gcTerminated: oldest terminated pods beyond the threshold
        if self.threshold > 0:
            terminated = [p for p in pods if p.phase in TERMINATED_PHASES]
            excess = len(terminated) - self.threshold
            if excess > 0:
                terminated.sort(key=lambda p: p.creation_timestamp)
                for p in terminated[:excess]:
                    deleted += self._delete(p, "terminated pods over threshold")
        # gcOrphaned: bound to a vanished node — force-delete with a
        # NodeLost audit record (the reference's node-lost eviction
        # reason); the pod's controller recreates it, and the recreated
        # pods sort by CREATION time in the scheduler's activeQ (pinned
        # by tests/test_node_churn.py, mirroring the crash-recovery
        # ordering contract)
        for p in pods:
            if p.node_name and p.node_name not in nodes:
                deleted += self._delete(
                    p, f"node {p.node_name} gone", event="NodeLost")
        # gcUnscheduledTerminating
        for p in pods:
            if p.deleted and not p.node_name:
                deleted += self._delete(p, "terminating and never scheduled")
        return deleted
