"""Job controller — pkg/controller/job/job_controller.go:69.

Run-to-completion reconciliation: keep `parallelism` pods active until
`completions` pods have Succeeded; count failures against `backoff_limit`
(exceeding it fails the Job and stops creating); finished Jobs with a TTL
are deleted by the ttl-after-finished sweep (reference:
pkg/controller/ttlafterfinished). Pods carry the `job-name` label the
reference's generated selector keys on.
"""
from __future__ import annotations

import itertools
import time as _time

from kubernetes_tpu.api.types import Job, Pod
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, JOBS, AlreadyExistsError, NotFoundError,
)

JOB_NAME_LABEL = "job-name"
_suffix = itertools.count(1)


class JobController(DirtyKeyController):
    KIND = JOBS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        from kubernetes_tpu.apiserver.admission import AdmissionChain
        self.admission = AdmissionChain()
        self.recorder = EventRecorder(store, component="controllermanager")

    def _register_extra_handlers(self) -> None:
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=self._pod_changed,
                               on_update=lambda o, n: self._pod_changed(n),
                               on_delete=self._pod_changed)

    def _pod_changed(self, pod: Pod) -> None:
        if pod.owner_ref is not None and pod.owner_ref[0] == "Job":
            self._dirty.add(f"{pod.namespace}/{pod.owner_ref[1]}")

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else _time.time()

    def pump(self) -> int:
        n = super().pump()
        n += self.sweep_finished()
        return n

    # -- syncJob -------------------------------------------------------------
    def _owned_pods(self, job: Job) -> list[Pod]:
        pods, _rv = self.store.list(PODS)
        return [p for p in pods
                if p.namespace == job.namespace and not p.deleted
                and p.owner_ref is not None
                and p.owner_ref[:2] == ("Job", job.name)]

    def reconcile(self, job: Job) -> None:
        pods = self._owned_pods(job)
        # completion and failure LATCH (a terminal Job never re-runs):
        # succeeded counts survive their pods — PodGC/namespace sweeps
        # deleting finished pods must not resurrect the workload
        succeeded = max(job.succeeded if job.complete else 0,
                        sum(1 for p in pods if p.phase == "Succeeded"))
        failed = max(job.failed,
                     sum(1 for p in pods if p.phase == "Failed"))
        active = [p for p in pods if p.phase not in ("Succeeded", "Failed")]
        complete = job.complete or succeeded >= job.completions
        job_failed = job.job_failed or failed > job.backoff_limit

        created = 0
        if not complete and not job_failed:
            # active pods cover the remaining completions up to parallelism
            want = min(job.parallelism, job.completions - succeeded)
            from kubernetes_tpu.apiserver.admission import AdmissionError
            for _ in range(max(0, want - len(active))):
                pod = self._template_pod(job)
                admitted = None
                try:
                    pod = admitted = self.admission.admit(PODS, pod, self.store)
                    self.store.create(PODS, pod)
                    created += 1
                except AlreadyExistsError:
                    self.admission.refund(PODS, admitted, self.store)
                    continue
                except AdmissionError as e:
                    self.recorder.event(
                        "Job", job.key, "Warning", "FailedCreate",
                        f"Error creating: {e}")
                    break
        elif active:
            # terminal job: active pods are torn down (job_controller.go
            # deletes running pods once the job fails; completed jobs have
            # no active pods by construction but clean up defensively)
            for p in active:
                try:
                    self.store.delete(PODS, p.key)
                except NotFoundError:
                    pass

        now = self._now()

        def mutate(cur):
            new_active = len(active) + created if not (complete or job_failed) else 0
            if (cur.active == new_active and cur.succeeded == succeeded
                    and cur.failed == failed and cur.complete == complete
                    and cur.job_failed == job_failed):
                return None
            cur.active = new_active
            cur.succeeded = succeeded
            cur.failed = failed
            if complete and not cur.complete:
                cur.completion_time = now
            if job_failed and not cur.job_failed and cur.completion_time is None:
                cur.completion_time = now
            cur.complete = complete
            cur.job_failed = job_failed
            return cur
        try:
            self.store.guaranteed_update(JOBS, job.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            return
        if complete and not job.complete:
            self.recorder.event("Job", job.key, NORMAL, "Completed",
                                f"Job completed ({succeeded} succeeded)")
        if job_failed and not job.job_failed:
            self.recorder.event(
                "Job", job.key, "Warning", "BackoffLimitExceeded",
                f"Job has reached the specified backoff limit "
                f"({failed} > {job.backoff_limit})")

    def _template_pod(self, job: Job) -> Pod:
        from kubernetes_tpu.api.types import PodTemplate
        tmpl = job.template or PodTemplate()
        return tmpl.make_pod(
            f"{job.name}-{next(_suffix):x}", job.namespace,
            owner_ref=("Job", job.name, f"job-{job.name}"),
            extra_labels={JOB_NAME_LABEL: job.name})

    # -- ttl-after-finished (pkg/controller/ttlafterfinished) ----------------
    def sweep_finished(self) -> int:
        n = 0
        now = self._now()
        for j in self.informers.informer(JOBS).list():
            if j.ttl_seconds_after_finished is None:
                continue
            if not (j.complete or j.job_failed) or j.completion_time is None:
                continue
            if now - j.completion_time >= j.ttl_seconds_after_finished:
                try:
                    self.store.delete(JOBS, j.key)
                    n += 1
                except NotFoundError:
                    pass
        return n
