"""ClusterRole aggregation controller —
pkg/controller/clusterroleaggregation/clusterroleaggregation_controller.go.

A ClusterRole with an aggregationRule owns no rules of its own: this loop
unions the rules of every ClusterRole whose labels match the rule's
selectors and writes them into the aggregated role (admin/edit/view are
built this way in the reference). Any role change re-evaluates every
aggregating role."""
from __future__ import annotations

from kubernetes_tpu.apiserver.auth import Role
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.store import Store, CLUSTERROLES, NotFoundError


def _matches(selector: dict, labels: dict) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class ClusterRoleAggregationController(DirtyKeyController):
    KIND = CLUSTERROLES

    def _register_extra_handlers(self) -> None:
        # ANY role event (including deletes and label REMOVALS, which the
        # new-labels-only match would miss) re-evaluates every aggregating
        # role — revocation must propagate, not just grants
        mark_aggregating = lambda *_: self._dirty.update(
            r.key for r in self.informers.informer(CLUSTERROLES).list()
            if r.aggregation_labels)
        self.informers.informer(CLUSTERROLES).add_event_handler(
            on_add=mark_aggregating,
            on_update=lambda o, n: mark_aggregating(),
            on_delete=mark_aggregating)

    def reconcile(self, role: Role) -> None:
        if not role.aggregation_labels:
            return   # sources are handled via the event fan-out above
        union: list = []
        seen = set()
        for other in sorted(self.informers.informer(CLUSTERROLES).list(),
                            key=lambda r: r.name):
            if other.name == role.name or other.aggregation_labels:
                continue
            if not _matches(role.aggregation_labels, other.labels):
                continue
            for rule in other.rules:
                if rule not in seen:
                    seen.add(rule)
                    union.append(rule)
        want = tuple(union)
        if want == role.rules:
            return

        def mutate(cur):
            if cur.rules == want:
                return None
            cur.rules = want
            return cur
        try:
            self.store.guaranteed_update(CLUSTERROLES, role.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass
