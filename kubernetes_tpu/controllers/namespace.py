"""Namespace + serviceaccount controllers.

- NamespaceController (pkg/controller/namespace/namespace_controller.go):
  a namespace in phase Terminating (set by the apiserver's DELETE
  finalization) has every namespaced object in it deleted, then the
  namespace object itself removed — the deletion cascade users observe as
  `kubectl delete namespace`.
- ServiceAccountController (pkg/controller/serviceaccount): every Active
  namespace gets a "default" ServiceAccount.
"""
from __future__ import annotations

from kubernetes_tpu.api.types import Namespace, ServiceAccount
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.store import (
    Store, NAMESPACES, SERVICEACCOUNTS, AlreadyExistsError, NotFoundError,
)
from kubernetes_tpu.api import serde


def namespaced_kinds() -> list[str]:
    """Every registered kind whose objects carry a namespace field — the
    discovery the reference does against the API surface
    (namespace_controller deletes 'all namespaced resources')."""
    return [k for k in serde.KIND_TYPES
            if k not in serde.CLUSTER_SCOPED_KINDS]


class NamespaceController(DirtyKeyController):
    KIND = NAMESPACES

    def reconcile(self, ns: Namespace) -> None:
        if ns.phase != "Terminating":
            return
        # deleteAllContent: every namespaced object in this namespace
        for kind in namespaced_kinds():
            objs, _rv = self.store.list(kind)
            for obj in objs:
                if getattr(obj, "namespace", None) != ns.name:
                    continue
                try:
                    self.store.delete(kind, obj.key)
                except NotFoundError:
                    pass
        try:
            self.store.delete(NAMESPACES, ns.key)
        except NotFoundError:
            pass


class ServiceAccountController(DirtyKeyController):
    """ensure_default: every Active namespace carries a 'default' SA
    (reference: pkg/controller/serviceaccount/serviceaccounts_controller.go)."""

    KIND = NAMESPACES

    def _register_extra_handlers(self) -> None:
        sa = self.informers.informer(SERVICEACCOUNTS)
        sa.add_event_handler(
            on_delete=lambda s: self._dirty.add(s.namespace))

    def reconcile(self, ns: Namespace) -> None:
        if ns.phase != "Active":
            return
        try:
            self.store.get(SERVICEACCOUNTS, f"{ns.name}/default")
        except NotFoundError:
            try:
                self.store.create(SERVICEACCOUNTS, ServiceAccount(
                    name="default", namespace=ns.name))
            except AlreadyExistsError:
                pass
