"""Horizontal pod autoscaler — pkg/controller/podautoscaler/horizontal.go.

v1 CPU-utilization semantics (replica_calculator.go GetResourceReplicas):
average the matched pods' CPU usage over their requests, take the ratio to
the target percentage, and scale the Deployment to
ceil(currentReplicas * ratio) inside [min, max] — skipping changes within
the 10% tolerance band so metric noise doesn't flap replica counts. The
usage feed is the store's `podmetrics` kind (the metrics.k8s.io
stand-in)."""
from __future__ import annotations

import math
import time as _time

from kubernetes_tpu.api.types import HorizontalPodAutoscaler, Pod
from kubernetes_tpu.api.types import get_resource_request
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL, WARNING
from kubernetes_tpu.store.store import (
    Store, DEPLOYMENTS, HPAS, PODS, PODMETRICS, NotFoundError,
)

TOLERANCE = 0.1          # horizontal.go tolerance


class HorizontalPodAutoscalerController(DirtyKeyController):
    KIND = HPAS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        self.recorder = EventRecorder(store, component="horizontal-pod-autoscaler")

    def _register_extra_handlers(self) -> None:
        # new usage samples re-evaluate every autoscaler (the reference
        # instead polls every 15s; event-driven keeps pump() deterministic)
        metrics = self.informers.informer(PODMETRICS)
        mark = lambda *_: self._dirty.update(
            h.key for h in self.informers.informer(HPAS).list())
        metrics.add_event_handler(on_add=mark, on_update=mark, on_delete=mark)

    def _now(self) -> float:
        return self.clock.now() if self.clock is not None else _time.time()

    def reconcile(self, hpa: HorizontalPodAutoscaler) -> None:
        kind, name = hpa.scale_target_ref
        if kind != "Deployment":
            return
        try:
            dep = self.store.get(DEPLOYMENTS, f"{hpa.namespace}/{name}")
        except NotFoundError:
            self.recorder.event("HorizontalPodAutoscaler", hpa.key, WARNING,
                                "FailedGetScale", f"{kind}/{name} not found")
            return
        if dep.selector is None:
            return
        pods = [p for p in self.store.list(PODS)[0]
                if p.namespace == hpa.namespace and not p.deleted
                and dep.selector.matches(p.labels)]
        utilizations = []
        missing = 0
        for p in pods:
            try:
                m = self.store.get(PODMETRICS, p.key)
            except NotFoundError:
                missing += 1
                continue
            req = get_resource_request(p).milli_cpu
            if req > 0:
                utilizations.append(100.0 * m.cpu_usage / req)
            else:
                missing += 1
        current = dep.replicas
        desired = current
        avg = None
        target = hpa.target_cpu_utilization
        n_all = len(utilizations) + missing
        if utilizations and target > 0:
            avg = sum(utilizations) / len(utilizations)
            ratio = avg / target
            if abs(ratio - 1.0) > TOLERANCE:
                if missing == 0:
                    # rebased on the measured population
                    # (replica_calculator.go calcPlainMetricReplicas)
                    desired = math.ceil(n_all * ratio)
                else:
                    # metric-less pods damp the move: they count as 0%
                    # usage on the way up and as FULL request utilization
                    # (100%) on the way down (replica_calculator.go:106) —
                    # filling with the target instead over-shrinks during
                    # rollouts whose fresh pods have no samples yet — and a
                    # move that flips direction (or lands in tolerance)
                    # after the fill is discarded
                    fill = 0.0 if ratio > 1.0 else 100.0
                    avg_all = (sum(utilizations) + fill * missing) / n_all
                    new_ratio = avg_all / target
                    if abs(new_ratio - 1.0) > TOLERANCE and \
                            (new_ratio > 1.0) == (ratio > 1.0):
                        desired = math.ceil(n_all * new_ratio)
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
        scaled = desired != current
        if scaled:
            def scale(cur):
                cur.replicas = desired
                return cur
            try:
                self.store.guaranteed_update(DEPLOYMENTS, dep.key, scale)
            except NotFoundError:
                return
            self.recorder.event(
                "HorizontalPodAutoscaler", hpa.key, NORMAL,
                "SuccessfulRescale",
                f"New size: {desired}; reason: cpu resource utilization "
                f"above/below target")

        util = int(round(avg)) if avg is not None else None

        def status(cur):
            if not scaled and cur.current_replicas == current \
                    and cur.desired_replicas == desired \
                    and cur.current_cpu_utilization == util:
                return None   # steady state: no write, no self-re-dirty
            cur.current_replicas = current
            cur.desired_replicas = desired
            cur.current_cpu_utilization = util
            if scaled:
                cur.last_scale_time = self._now()
            return cur
        try:
            self.store.guaranteed_update(HPAS, hpa.key, status,
                                         allow_skip=True)
        except NotFoundError:
            pass
