"""Garbage collector — pkg/controller/garbagecollector/garbagecollector.go:65.

The ownerReferences cascade: objects whose owner no longer exists are
deleted. The reference builds a live dependency graph from informers and
processes "virtual delete" events; this walks the same ownership edges —
pods owned by ReplicaSets/Jobs/DaemonSets/StatefulSets, ReplicaSets owned
by Deployments — deleting orphaned dependents (cascading: deleting a
Deployment removes its ReplicaSets on the next pass, whose pods go the
pass after; pump_until-style callers converge in <= depth passes, and the
controller marks itself dirty while any deletion happened so ControllerManager
loops converge in one call).
"""
from __future__ import annotations

from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.store import (
    Store, PODS, REPLICASETS, DEPLOYMENTS, JOBS, DAEMONSETS, STATEFULSETS,
    CRONJOBS, NotFoundError,
)

# owner kind name (as written in owner_ref[0]) -> store kind
OWNER_KINDS = {
    "ReplicaSet": REPLICASETS,
    "Deployment": DEPLOYMENTS,
    "Job": JOBS,
    "DaemonSet": DAEMONSETS,
    "StatefulSet": STATEFULSETS,
    "CronJob": CRONJOBS,
}
# kinds whose objects may carry owner_ref (the dependents we scan)
DEPENDENT_KINDS = (PODS, REPLICASETS, JOBS)


class GarbageCollector:
    def __init__(self, store: Store, clock=None):
        self.store = store
        self.informers = InformerFactory(store)
        self._deleted_owner = False
        for kind in OWNER_KINDS.values():
            inf = self.informers.informer(kind)
            inf.add_event_handler(on_delete=self._owner_deleted)

    def _owner_deleted(self, _obj) -> None:
        self._deleted_owner = True

    def sync(self) -> None:
        self.informers.sync_all()
        self.collect()

    def pump(self) -> int:
        self.informers.pump_all()
        if not self._deleted_owner:
            return 0
        self._deleted_owner = False
        return self.collect()

    def collect(self) -> int:
        """One full mark pass; repeats while deletions cascade."""
        total = 0
        while True:
            n = self._collect_once()
            total += n
            if n == 0:
                return total

    def _collect_once(self) -> int:
        n = 0
        for kind in DEPENDENT_KINDS:
            objs, _rv = self.store.list(kind)
            for obj in objs:
                ref = getattr(obj, "owner_ref", None)
                if ref is None:
                    continue
                owner_kind = OWNER_KINDS.get(ref[0])
                if owner_kind is None:
                    continue
                owner_key = f"{obj.namespace}/{ref[1]}"
                try:
                    self.store.get(owner_kind, owner_key)
                except NotFoundError:
                    try:
                        self.store.delete(kind, obj.key)
                        n += 1
                    except NotFoundError:
                        pass
        return n
