"""Node lifecycle controller — heartbeat-lease health grading, condition
taints, and zone-aware rate-limited NoExecute eviction.

Mirror of pkg/controller/nodelifecycle (node_lifecycle_controller.go with
TaintBasedEvictions + TaintNodesByCondition on, the v1.15 default stance
the scheduler's predicate set assumes):

- heartbeat leases (monitorNodeHealth): every node agent renews a
  coordination Lease (`node-<name>`, api.types.node_lease_key) on its
  clock; a node whose lease is staler than `node_monitor_grace` grades
  Ready=Unknown — no status-field polling. The agent's own heartbeat
  restores Ready=True on recovery.
- condition -> taint sync: a node whose Ready condition is False gets the
  `node.kubernetes.io/not-ready` NoSchedule + NoExecute taints; Unknown
  gets `node.kubernetes.io/unreachable`; a Ready node has both removed
  (controller doNoScheduleTaintingPass / doNoExecuteTaintingPass).
- zone-aware rate-limited eviction (NoExecuteTaintManager +
  handleDisruption): pods due for NoExecute eviction enter a PER-ZONE
  queue drained through per-zone token buckets. Zone health grades the
  rate: Normal -> `eviction_rate`, PartialDisruption (notReady fraction
  >= `unhealthy_zone_threshold`) -> `secondary_eviction_rate`,
  FullDisruption (no ready node — or a disconnected master, which reads
  as every zone fully disrupted) -> ZERO evictions. Deliberate deviation
  from the reference: an isolated fully-disrupted zone also stops
  evicting (the reference evicts it at the primary rate); this repo's
  contract is that mass-failure never mass-evicts.
- every eviction routes through the PDB-guarded `Store.evict_pod`
  subresource verb: a pod whose disruption budget is exhausted is
  refused (429 semantics) and retried on a later pump — no eviction ever
  lands while `disruptionsAllowed == 0`.
"""
from __future__ import annotations

from collections import deque
from typing import Optional

from kubernetes_tpu import obs
from kubernetes_tpu.api.types import (
    Node, Pod, Taint, NO_SCHEDULE, NO_EXECUTE,
    LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION, node_lease_key,
)
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, NODES, DisruptionBudgetError, NotFoundError,
)
from kubernetes_tpu.utils.clock import Clock, RealClock

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
_LIFECYCLE_KEYS = (TAINT_NOT_READY, TAINT_UNREACHABLE)

# zone disruption states (nodelifecycle zoneState analogs)
STATE_NORMAL = "Normal"
STATE_PARTIAL = "PartialDisruption"
STATE_FULL = "FullDisruption"
_STATE_CODE = {STATE_NORMAL: 0, STATE_PARTIAL: 1, STATE_FULL: 2}

ZONE_STATE = obs.gauge(
    "zone_disruption_state",
    "Disruption grade per failure zone: 0 = Normal (primary eviction "
    "rate), 1 = PartialDisruption (secondary rate), 2 = FullDisruption "
    "(zero evictions).", ("zone",))


class TokenBucket:
    """flowcontrol.NewTokenBucketRateLimiter analog on an injected
    timestamp (the controller passes its Clock's now()): `rate` tokens
    per second up to `burst`. `refund()` returns a token a refused
    eviction consumed (budget-exhausted pods must not burn the zone's
    pace)."""

    def __init__(self, rate: float, burst: float = 1.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last: Optional[float] = None

    def set_rate(self, rate: float) -> None:
        self.rate = float(rate)

    def _advance(self, now: float) -> None:
        if self._last is None:
            self._last = now
        if now > self._last:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now

    def try_take(self, now: float) -> bool:
        self._advance(now)
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def refund(self) -> None:
        self._tokens = min(self.burst, self._tokens + 1.0)

    def tokens(self, now: float) -> float:
        self._advance(now)
        return self._tokens


def _zone_of(node: Node) -> str:
    """Human-readable failure-zone key for pacing/metrics ("region/zone",
    or whichever half is labeled; "" = unzoned). Deliberately NOT
    get_zone_key's \\x00-joined form — these names surface in /metrics
    labels and /debug/sched."""
    region = node.labels.get(LABEL_ZONE_REGION, "")
    zone = node.labels.get(LABEL_ZONE_FAILURE_DOMAIN, "")
    if region and zone:
        return f"{region}/{zone}"
    return region or zone


def _ready_status(node: Node) -> str:
    for c in node.conditions:
        if c.type == "Ready":
            return c.status
    return "True"   # no condition reported = treated schedulable


def _wanted_taints(node: Node) -> tuple[Taint, ...]:
    status = _ready_status(node)
    if status == "False":
        return (Taint(key=TAINT_NOT_READY, effect=NO_SCHEDULE),
                Taint(key=TAINT_NOT_READY, effect=NO_EXECUTE))
    if status == "Unknown":
        return (Taint(key=TAINT_UNREACHABLE, effect=NO_SCHEDULE),
                Taint(key=TAINT_UNREACHABLE, effect=NO_EXECUTE))
    return ()


class NodeLifecycleController:
    # a node whose lease heartbeat is this stale reads Ready=Unknown
    # (reference: node-monitor-grace-period, 40s default); kept as a class
    # attribute for back-compat with callers that override it
    NODE_MONITOR_GRACE = 40.0

    def __init__(self, store: Store, clock: Optional[Clock] = None,
                 eviction_rate: float = 0.1,
                 secondary_eviction_rate: float = 0.01,
                 eviction_burst: float = 1.0,
                 unhealthy_zone_threshold: float = 0.55,
                 node_monitor_grace: Optional[float] = None):
        self.store = store
        self.clock = clock or RealClock()
        self.eviction_rate = float(eviction_rate)
        self.secondary_eviction_rate = float(secondary_eviction_rate)
        self.eviction_burst = float(eviction_burst)
        self.unhealthy_zone_threshold = float(unhealthy_zone_threshold)
        self.node_monitor_grace = (self.NODE_MONITOR_GRACE
                                   if node_monitor_grace is None
                                   else float(node_monitor_grace))
        self.recorder = EventRecorder(store, component="controllermanager")
        self.informers = InformerFactory(store)
        self._dirty_nodes: set[str] = set()
        # node -> NoExecute taint keys -> time first observed (for bounded
        # tolerationSeconds eviction)
        self._noexec_since: dict[str, dict[str, float]] = {}
        # zone-paced eviction plane: per-zone FIFO of (pod_key, node_name)
        # due for NoExecute eviction, per-zone token buckets, and the
        # latest zone grades (the /debug/sched section's content)
        self._evict_q: dict[str, deque] = {}
        self._queued: set[str] = set()
        self._pacers: dict[str, TokenBucket] = {}
        self._zone_state: dict[str, str] = {}
        self._evicted_by_zone: dict[str, int] = {}
        nodes = self.informers.informer(NODES)
        nodes.add_event_handler(
            on_add=lambda n: self._dirty_nodes.add(n.name),
            on_update=lambda o, n: self._dirty_nodes.add(n.name),
            on_delete=lambda n: (self._dirty_nodes.discard(n.name),
                                 self._noexec_since.pop(n.name, None)))
        pods = self.informers.informer(PODS)
        pods.add_event_handler(
            on_add=lambda p: p.node_name and self._dirty_nodes.add(p.node_name),
            on_update=lambda o, n: n.node_name
            and self._dirty_nodes.add(n.node_name),
            on_delete=lambda p: None)
        self._register_debug()

    def _register_debug(self) -> None:
        """Publish zone grades + pacer tokens + queue depths as a
        /debug/sched section (weakref-held: a dropped controller's
        section disappears instead of pinning the object graph)."""
        import weakref
        ref = weakref.ref(self)

        def snap():
            c = ref()
            return None if c is None else c.debug_state()
        obs.register_debug("nodelifecycle", snap)

    def debug_state(self) -> dict:
        now = self.clock.now()
        zones = {}
        for zone in set(self._zone_state) | set(self._evict_q) \
                | set(self._pacers):
            pacer = self._pacers.get(zone)
            zones[zone] = {
                "state": self._zone_state.get(zone, STATE_NORMAL),
                "rate": pacer.rate if pacer is not None else None,
                "tokens": (round(pacer.tokens(now), 3)
                           if pacer is not None else None),
                "queued": len(self._evict_q.get(zone, ())),
                "evicted": self._evicted_by_zone.get(zone, 0),
            }
        return {"zones": zones,
                "eviction_rate": self.eviction_rate,
                "secondary_eviction_rate": self.secondary_eviction_rate}

    def sync(self) -> None:
        self.informers.sync_all()
        for n in self.informers.informer(NODES).list():
            self._dirty_nodes.add(n.name)
        self.reconcile_dirty()

    def monitor_node_health(self) -> None:
        """monitorNodeHealth analog: grade nodes whose kubelet heartbeat
        (node Lease renewal) has gone silent past the grace period as
        Ready=Unknown; the condition->taint pass then isolates them. The
        kubelet's own heartbeat restores Ready=True on recovery."""
        from kubernetes_tpu.store.store import LEASES
        from kubernetes_tpu.api.types import NodeCondition
        now = self.clock.now()
        leases = {l.holder: l for l in self.store.list(LEASES)[0]
                  if l.name.startswith("node-")}
        nodes, _rv = self.store.list(NODES)
        for node in nodes:
            lease = leases.get(node.name)
            if lease is None or lease.name != node_lease_key(node.name):
                continue   # never heartbeated: static fixture node
            status = _ready_status(node)
            if now - lease.renew_time <= self.node_monitor_grace:
                continue
            if status == "Unknown":
                continue

            def grade(cur):
                conds = [c for c in cur.conditions if c.type != "Ready"]
                conds.append(NodeCondition(type="Ready", status="Unknown"))
                cur.conditions = tuple(conds)
                return cur
            try:
                self.store.guaranteed_update(NODES, node.name, grade)
            except NotFoundError:
                continue
            self.recorder.event(
                "Node", node.name, NORMAL, "NodeNotReady",
                f"Node {node.name} hasn't heartbeated in "
                f"{now - lease.renew_time:.0f}s")
            self._dirty_nodes.add(node.name)
        self._update_zone_states()

    # -- zone disruption grading (handleDisruption analog) -------------------
    def _update_zone_states(self) -> None:
        nodes, _rv = self.store.list(NODES)
        by_zone: dict[str, list[Node]] = {}
        for n in nodes:
            by_zone.setdefault(_zone_of(n), []).append(n)
        states: dict[str, str] = {}
        for zone, members in by_zone.items():
            not_ready = sum(1 for n in members
                            if _ready_status(n) != "True")
            if members and not_ready == len(members):
                state = STATE_FULL
            elif len(members) > 0 and \
                    not_ready / len(members) >= self.unhealthy_zone_threshold:
                state = STATE_PARTIAL
            else:
                state = STATE_NORMAL
            states[zone] = state
            ZONE_STATE.labels(zone or "<unzoned>").set(_STATE_CODE[state])
            pacer = self._pacers.get(zone)
            if pacer is None:
                pacer = self._pacers[zone] = TokenBucket(
                    self.eviction_rate, self.eviction_burst)
            pacer.set_rate(self._rate_for(state))
        # zones whose last node vanished: drop grades (their queued
        # evictions resolve as no-longer-due / orphaned at drain)
        for zone in list(self._zone_state):
            if zone not in states:
                del self._zone_state[zone]
                ZONE_STATE.labels(zone or "<unzoned>").set(0)
        self._zone_state = states

    def _rate_for(self, state: str) -> float:
        if state == STATE_FULL:
            return 0.0
        if state == STATE_PARTIAL:
            return self.secondary_eviction_rate
        return self.eviction_rate

    def pump(self) -> int:
        self.informers.pump_all()
        self.monitor_node_health()
        # bounded-toleration evictions fire on time, not on events
        for name in list(self._noexec_since):
            self._dirty_nodes.add(name)
        n = self.reconcile_dirty()
        self.drain_evictions()
        return n

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty_nodes:
            name = self._dirty_nodes.pop()
            try:
                node = self.store.get(NODES, name)
            except NotFoundError:
                self._noexec_since.pop(name, None)
                continue
            self._sync_taints(node)
            n += 1
        return n

    # -- condition -> taint (doNoSchedule/doNoExecuteTaintingPass) ----------
    def _sync_taints(self, node: Node) -> None:
        wanted = _wanted_taints(node)
        kept = tuple(t for t in node.taints if t.key not in _LIFECYCLE_KEYS)
        new = kept + wanted
        if tuple(sorted(new, key=repr)) != tuple(sorted(node.taints, key=repr)):
            def mutate(cur):
                cur.taints = tuple(
                    t for t in cur.taints
                    if t.key not in _LIFECYCLE_KEYS) + wanted
                return cur
            try:
                node = self.store.guaranteed_update(NODES, node.name, mutate)
            except NotFoundError:
                return
            if wanted:
                self.recorder.event(
                    "Node", node.name, NORMAL, "NodeNotReady" if
                    _ready_status(node) == "False" else "NodeNotReachable",
                    f"Node {node.name} tainted {wanted[0].key}")
        self._queue_noexecute_evictions(node)

    # -- NoExecute taint manager: queue side ----------------------------------
    def _queue_noexecute_evictions(self, node: Node) -> None:
        """Track NoExecute taints' first-seen times and enqueue pods past
        their toleration deadline into the node's ZONE eviction queue
        (the paced drain below performs the actual evictions)."""
        noexec = [t for t in node.taints if t.effect == NO_EXECUTE]
        since = self._noexec_since.setdefault(node.name, {})
        now = self.clock.now()
        live = set()
        for t in noexec:
            live.add(t.key)
            since.setdefault(t.key, now)
        for k in list(since):
            if k not in live:
                del since[k]
        if not noexec:
            if not since:
                self._noexec_since.pop(node.name, None)
            return
        zone = _zone_of(node)
        pods, _rv = self.store.list(PODS)
        for pod in pods:
            if pod.node_name != node.name or pod.deleted \
                    or pod.key in self._queued:
                continue
            deadline = self._eviction_deadline(pod, noexec, since)
            if deadline is None or deadline > now:
                continue
            self._evict_q.setdefault(zone, deque()).append(
                (pod.key, node.name))
            self._queued.add(pod.key)

    # -- NoExecute taint manager: paced drain ---------------------------------
    def drain_evictions(self) -> int:
        """Drain each zone's eviction queue through its token bucket, one
        batched `store.evict_many` per zone per tick (round 23): the tick
        takes as many tokens as it has due pods (up to the bucket), lands
        them in ONE store critical section, then settles outcomes —
        "refused" and "skipped" pods refund their tokens and stay queued
        IN ORDER for a later pump (stop_on_refusal preserves the serial
        path's head-of-line pacing: nothing behind a budget-blocked pod
        jumps it). A FullDisruption zone (rate 0) performs zero
        evictions. Returns pods evicted."""
        now = self.clock.now()
        evicted = 0
        for zone, q in self._evict_q.items():
            pacer = self._pacers.get(zone)
            if pacer is None:
                pacer = self._pacers[zone] = TokenBucket(
                    self.eviction_rate, self.eviction_burst)
            if pacer.rate <= 0.0:
                continue
            batch: list = []   # (pod_key, node_name, pod) — tokens taken
            while q:
                pod_key, node_name = q[0]
                pod = self._still_due(pod_key, node_name, now)
                if pod is None:
                    q.popleft()
                    self._queued.discard(pod_key)
                    continue
                if not pacer.try_take(now):
                    break
                batch.append((pod_key, node_name, pod))
                q.popleft()
            if not batch:
                continue
            outcomes = self.store.evict_many(
                [k for k, _n, _p in batch], reason="taint-manager",
                stop_on_refusal=True)
            requeue: list = []
            for pod_key, node_name, pod in batch:
                out = outcomes.get(pod_key, "missing")
                if out == "evicted":
                    self._queued.discard(pod_key)
                    evicted += 1
                    self._evicted_by_zone[zone] = \
                        self._evicted_by_zone.get(zone, 0) + 1
                    self.recorder.pod_event(
                        pod, NORMAL, "TaintManagerEviction",
                        f"Deleting pod {pod_key} from node {node_name}")
                elif out == "missing":
                    # vanished between the due-check and the write: the
                    # serial path consumed the token here too (no refund)
                    self._queued.discard(pod_key)
                else:   # refused (budget) or skipped (behind a refusal)
                    pacer.refund()
                    requeue.append((pod_key, node_name))
            for item in reversed(requeue):
                q.appendleft(item)
        return evicted

    def _still_due(self, pod_key: str, node_name: str,
                   now: float) -> Optional[Pod]:
        """Re-validate a queued eviction at drain time: the taint may have
        cleared, the pod may have moved/vanished, the node may be gone
        (podgc's orphan sweep owns that case). Returns the pod when the
        eviction is still due, else None."""
        try:
            node = self.store.get(NODES, node_name)
        except NotFoundError:
            return None
        noexec = [t for t in node.taints if t.effect == NO_EXECUTE]
        if not noexec:
            return None
        try:
            pod = self.store.get(PODS, pod_key)
        except NotFoundError:
            return None
        if pod.node_name != node_name or pod.deleted:
            return None
        since = self._noexec_since.get(node_name, {})
        deadline = self._eviction_deadline(pod, noexec, since)
        if deadline is not None and deadline <= now:
            return pod
        return None

    @staticmethod
    def _eviction_deadline(pod: Pod, noexec: list[Taint],
                           since: dict[str, float]) -> Optional[float]:
        """Earliest time the pod must go; None = tolerates forever.
        Reference: NoExecuteTaintManager processPodOnNode — a pod must
        tolerate EVERY NoExecute taint; the usable toleration window is
        the minimum tolerationSeconds across them. Pinned semantics
        (tests/test_node_churn.py table): no matching toleration = evict
        immediately; tolerationSeconds absent on every matching
        toleration = never evict; 0 = immediate; negative = clamped to 0
        (immediate), matching the reference's negative-seconds handling."""
        deadline = None
        for t in noexec:
            tols = [tol for tol in pod.tolerations if tol.tolerates(t)]
            if not tols:
                return 0.0          # evict now
            secs = [tol.toleration_seconds for tol in tols
                    if getattr(tol, "toleration_seconds", None) is not None]
            if secs:
                d = since.get(t.key, 0.0) + max(0.0, min(secs))
                deadline = d if deadline is None else min(deadline, d)
        return deadline
