"""Node lifecycle controller — failure detection, condition taints, taint
eviction.

Mirror of pkg/controller/nodelifecycle (node_lifecycle_controller.go with
TaintBasedEvictions + TaintNodesByCondition on, the v1.15 default stance
the scheduler's predicate set assumes):

- condition -> taint sync: a node whose Ready condition is False gets the
  `node.kubernetes.io/not-ready` NoSchedule + NoExecute taints; Unknown gets
  `node.kubernetes.io/unreachable`; a Ready node has both removed
  (nodelifecycle/scheduler/... taintToleratedBySelector; controller
  doNoScheduleTaintingPass / doNoExecuteTaintingPass).
- taint eviction (NoExecuteTaintManager): pods on a node carrying a
  NoExecute taint they do not tolerate are deleted. Pods tolerating it with
  a bounded tolerationSeconds are deleted once the taint has been in place
  that long (checked per pump against the injected clock).

Heartbeat/grace-period machinery is out of scope: with no kubelet, Ready
transitions arrive as explicit condition updates through the store (the
hollow-node generator and tests flip them), and this controller reacts.
"""
from __future__ import annotations

import time as _time
from typing import Optional

from kubernetes_tpu.api.types import (
    Node, Pod, Taint, NO_SCHEDULE, NO_EXECUTE,
)
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import Store, PODS, NODES, NotFoundError
from kubernetes_tpu.utils.clock import Clock, RealClock

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
_LIFECYCLE_KEYS = (TAINT_NOT_READY, TAINT_UNREACHABLE)


def _ready_status(node: Node) -> str:
    for c in node.conditions:
        if c.type == "Ready":
            return c.status
    return "True"   # no condition reported = treated schedulable


def _wanted_taints(node: Node) -> tuple[Taint, ...]:
    status = _ready_status(node)
    if status == "False":
        return (Taint(key=TAINT_NOT_READY, effect=NO_SCHEDULE),
                Taint(key=TAINT_NOT_READY, effect=NO_EXECUTE))
    if status == "Unknown":
        return (Taint(key=TAINT_UNREACHABLE, effect=NO_SCHEDULE),
                Taint(key=TAINT_UNREACHABLE, effect=NO_EXECUTE))
    return ()


class NodeLifecycleController:
    def __init__(self, store: Store, clock: Optional[Clock] = None):
        self.store = store
        self.clock = clock or RealClock()
        self.recorder = EventRecorder(store, component="controllermanager")
        self.informers = InformerFactory(store)
        self._dirty_nodes: set[str] = set()
        # node -> NoExecute taint keys -> time first observed (for bounded
        # tolerationSeconds eviction)
        self._noexec_since: dict[str, dict[str, float]] = {}
        nodes = self.informers.informer(NODES)
        nodes.add_event_handler(
            on_add=lambda n: self._dirty_nodes.add(n.name),
            on_update=lambda o, n: self._dirty_nodes.add(n.name),
            on_delete=lambda n: (self._dirty_nodes.discard(n.name),
                                 self._noexec_since.pop(n.name, None)))
        pods = self.informers.informer(PODS)
        pods.add_event_handler(
            on_add=lambda p: p.node_name and self._dirty_nodes.add(p.node_name),
            on_update=lambda o, n: n.node_name
            and self._dirty_nodes.add(n.node_name),
            on_delete=lambda p: None)

    def sync(self) -> None:
        self.informers.sync_all()
        for n in self.informers.informer(NODES).list():
            self._dirty_nodes.add(n.name)
        self.reconcile_dirty()

    # a node whose lease heartbeat is this stale reads Ready=Unknown
    # (reference: node-monitor-grace-period, 40s default)
    NODE_MONITOR_GRACE = 40.0

    def monitor_node_health(self) -> None:
        """monitorNodeHealth analog: grade nodes whose kubelet heartbeat
        (node Lease renewal) has gone silent past the grace period as
        Ready=Unknown; the condition->taint pass then isolates them. The
        kubelet's own heartbeat restores Ready=True on recovery."""
        from kubernetes_tpu.store.store import LEASES
        from kubernetes_tpu.api.types import NodeCondition
        now = self.clock.now()
        leases = {l.holder: l for l in self.store.list(LEASES)[0]
                  if l.name.startswith("node-")}
        for node in self.store.list(NODES)[0]:
            lease = leases.get(node.name)
            if lease is None:
                continue   # never heartbeated: static fixture node
            status = _ready_status(node)
            if now - lease.renew_time <= self.NODE_MONITOR_GRACE:
                continue
            if status == "Unknown":
                continue

            def grade(cur):
                conds = [c for c in cur.conditions if c.type != "Ready"]
                conds.append(NodeCondition(type="Ready", status="Unknown"))
                cur.conditions = tuple(conds)
                return cur
            try:
                self.store.guaranteed_update(NODES, node.name, grade)
            except NotFoundError:
                continue
            self.recorder.event(
                "Node", node.name, NORMAL, "NodeNotReady",
                f"Node {node.name} hasn't heartbeated in "
                f"{now - lease.renew_time:.0f}s")
            self._dirty_nodes.add(node.name)

    def pump(self) -> int:
        self.informers.pump_all()
        self.monitor_node_health()
        # bounded-toleration evictions fire on time, not on events
        for name in list(self._noexec_since):
            self._dirty_nodes.add(name)
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty_nodes:
            name = self._dirty_nodes.pop()
            try:
                node = self.store.get(NODES, name)
            except NotFoundError:
                self._noexec_since.pop(name, None)
                continue
            self._sync_taints(node)
            n += 1
        return n

    # -- condition -> taint (doNoSchedule/doNoExecuteTaintingPass) ----------
    def _sync_taints(self, node: Node) -> None:
        wanted = _wanted_taints(node)
        kept = tuple(t for t in node.taints if t.key not in _LIFECYCLE_KEYS)
        new = kept + wanted
        if tuple(sorted(new, key=repr)) != tuple(sorted(node.taints, key=repr)):
            def mutate(cur):
                cur.taints = tuple(
                    t for t in cur.taints
                    if t.key not in _LIFECYCLE_KEYS) + wanted
                return cur
            try:
                node = self.store.guaranteed_update(NODES, node.name, mutate)
            except NotFoundError:
                return
            if wanted:
                self.recorder.event(
                    "Node", node.name, NORMAL, "NodeNotReady" if
                    _ready_status(node) == "False" else "NodeNotReachable",
                    f"Node {node.name} tainted {wanted[0].key}")
        self._evict_for_noexecute(node)

    # -- NoExecute taint manager --------------------------------------------
    def _evict_for_noexecute(self, node: Node) -> None:
        noexec = [t for t in node.taints if t.effect == NO_EXECUTE]
        since = self._noexec_since.setdefault(node.name, {})
        now = self.clock.now()
        live = set()
        for t in noexec:
            live.add(t.key)
            since.setdefault(t.key, now)
        for k in list(since):
            if k not in live:
                del since[k]
        if not noexec:
            if not since:
                self._noexec_since.pop(node.name, None)
            return
        pods, _rv = self.store.list(PODS)
        for pod in pods:
            if pod.node_name != node.name or pod.deleted:
                continue
            deadline = self._eviction_deadline(pod, noexec, since)
            if deadline is None or deadline > now:
                continue
            try:
                self.store.delete(PODS, pod.key)
            except NotFoundError:
                continue
            self.recorder.pod_event(
                pod, NORMAL, "TaintManagerEviction",
                f"Deleting pod {pod.key} from node {node.name}")

    @staticmethod
    def _eviction_deadline(pod: Pod, noexec: list[Taint],
                           since: dict[str, float]) -> Optional[float]:
        """Earliest time the pod must go; None = tolerates forever.
        Reference: NoExecuteTaintManager processPodOnNode — a pod must
        tolerate EVERY NoExecute taint; the usable toleration window is the
        minimum tolerationSeconds across them."""
        deadline = None
        for t in noexec:
            tols = [tol for tol in pod.tolerations if tol.tolerates(t)]
            if not tols:
                return 0.0          # evict now
            secs = [tol.toleration_seconds for tol in tols
                    if getattr(tol, "toleration_seconds", None) is not None]
            if secs:
                d = since.get(t.key, 0.0) + min(secs)
                deadline = d if deadline is None else min(deadline, d)
        return deadline
