"""Disruption controller — reconciles PodDisruptionBudget status from pod
state through the store.

Mirror of pkg/controller/disruption/disruption.go (trySync :496,
getExpectedPodCount :526, getExpectedScale :569, countHealthyPods :615,
updatePdbStatus :683): watch pods + PDBs, recompute
{expectedPods, currentHealthy, desiredHealthy, disruptionsAllowed} and write
the status back only when it changed. PDB-aware preemption
(pickOneNodeForPreemption's minPDBviolations criterion) reads the
reconciled `disruptions_allowed` — before this controller, that field was a
static literal nothing maintained.

Pruning notes vs the reference:
- "healthy" is an explicit Ready condition when present, else simply
  "bound" (no kubelet exists to report readiness).
- the expected-scale walk resolves a pod's single controller via
  `owner_ref` against the ReplicaSet stand-in's `replicas` (the reference
  consults RC/RS/Deployment/StatefulSet scale subresources).
- disruptedPods eviction-in-flight bookkeeping is out of scope (no /evict
  subresource here; the scheduler deletes victims directly).
"""
from __future__ import annotations

import math
from typing import Optional

from kubernetes_tpu.api.types import Pod, PodDisruptionBudget
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.record import EventRecorder, NORMAL, WARNING
from kubernetes_tpu.store.store import Store, PODS, PDBS, REPLICASETS, NotFoundError


def _value_from_int_or_percent(value, total: int, round_up: bool) -> int:
    """apimachinery intstr.GetValueFromIntOrPercent."""
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if s.endswith("%"):
        pct = int(s[:-1])
        v = pct * total / 100.0
        return math.ceil(v) if round_up else math.floor(v)
    return int(s)


def _is_healthy(pod: Pod) -> bool:
    for c in pod.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return bool(pod.node_name)


class DisruptionController:
    """One reconcile loop of the 31 in controllermanager.go:372-412."""

    def __init__(self, store: Store):
        self.store = store
        self.recorder = EventRecorder(store, component="controllermanager")
        self.informers = InformerFactory(store)
        self._dirty: set[str] = set()
        pods = self.informers.informer(PODS)
        # any pod change may move any budget's healthy count; PDBs are few,
        # so dirty them all (the reference maps pod->pdb via selector lookup)
        pods.add_event_handler(on_add=lambda p: self._mark_all(),
                               on_update=lambda o, n: self._mark_all(),
                               on_delete=lambda p: self._mark_all())
        pdbs = self.informers.informer(PDBS)
        pdbs.add_event_handler(on_add=lambda b: self._dirty.add(b.key),
                               on_update=lambda o, n: self._dirty.add(n.key),
                               on_delete=lambda b: self._dirty.discard(b.key))
        rs = self.informers.informer(REPLICASETS)
        rs.add_event_handler(on_add=lambda r: self._mark_all(),
                             on_update=lambda o, n: self._mark_all(),
                             on_delete=lambda r: self._mark_all())

    def _mark_all(self) -> None:
        for b in self.informers.informer(PDBS).list():
            self._dirty.add(b.key)

    # -- lifecycle -----------------------------------------------------------
    def sync(self) -> None:
        self.informers.sync_all()
        self._mark_all()
        self.reconcile_dirty()

    def pump(self) -> int:
        """Drain informer events, reconcile dirty budgets; returns number
        reconciled."""
        self.informers.pump_all()
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty:
            key = self._dirty.pop()
            try:
                pdb = self.store.get(PDBS, key)
            except NotFoundError:
                continue
            self.try_sync(pdb)
            n += 1
        return n

    # -- reconcile (trySync :496) --------------------------------------------
    def _pods_for_pdb(self, pdb: PodDisruptionBudget) -> list[Pod]:
        if pdb.selector is None:
            return []
        pods, _rv = self.store.list(PODS)
        return [p for p in pods
                if p.namespace == pdb.namespace
                and not p.deleted
                and pdb.selector.matches(p.labels)]

    def _expected_scale(self, pdb: PodDisruptionBudget,
                        pods: list[Pod]) -> Optional[int]:
        """getExpectedScale :569 — sum of scales of the pods' controllers;
        None (error) when any pod has no controller."""
        controllers: dict[str, int] = {}
        rss, _rv = self.store.list(REPLICASETS)
        by_name = {(r.namespace, r.name): r for r in rss}
        for pod in pods:
            if pod.owner_ref is None:
                return None
            _kind, name, _uid = pod.owner_ref
            rs = by_name.get((pod.namespace, name))
            if rs is None:
                return None
            controllers[rs.key] = rs.replicas
        return sum(controllers.values())

    def _expected_pod_count(self, pdb: PodDisruptionBudget, pods: list[Pod]
                            ) -> Optional[tuple[int, int]]:
        """getExpectedPodCount :526 -> (expected, desired_healthy)."""
        if pdb.max_unavailable is not None:
            scale = self._expected_scale(pdb, pods)
            if scale is None:
                return None
            max_unavail = _value_from_int_or_percent(
                pdb.max_unavailable, scale, True)
            return scale, max(scale - max_unavail, 0)
        if pdb.min_available is not None:
            if isinstance(pdb.min_available, int):
                return len(pods), pdb.min_available
            scale = self._expected_scale(pdb, pods)
            if scale is None:
                return None
            return scale, _value_from_int_or_percent(
                pdb.min_available, scale, True)
        return None   # no spec: leave the status alone (pruned-type compat)

    def try_sync(self, pdb: PodDisruptionBudget) -> None:
        pods = self._pods_for_pdb(pdb)
        if not pods:
            self.recorder.event("PodDisruptionBudget", pdb.key, NORMAL,
                                "NoPods", "No matching pods found")
        counts = self._expected_pod_count(pdb, pods)
        if counts is None:
            if pdb.min_available is None and pdb.max_unavailable is None:
                return
            # failSafe :676: fail closed — no disruptions while confused
            self.recorder.event(
                "PodDisruptionBudget", pdb.key, WARNING,
                "CalculateExpectedPodCountFailed",
                "Failed to calculate the number of expected pods")
            self._update_status(pdb, pdb.current_healthy, pdb.desired_healthy,
                                pdb.expected_pods, 0)
            return
        expected, desired = counts
        healthy = sum(1 for p in pods if _is_healthy(p))
        allowed = healthy - desired
        if expected <= 0 or allowed <= 0:
            allowed = 0
        self._update_status(pdb, healthy, desired, expected, allowed)

    def _update_status(self, pdb: PodDisruptionBudget, healthy: int,
                       desired: int, expected: int, allowed: int) -> None:
        if (pdb.current_healthy == healthy and pdb.desired_healthy == desired
                and pdb.expected_pods == expected
                and pdb.disruptions_allowed == allowed):
            return   # updatePdbStatus :689 skips no-op writes
        def mutate(cur):
            cur.current_healthy = healthy
            cur.desired_healthy = desired
            cur.expected_pods = expected
            cur.disruptions_allowed = allowed
            return cur
        try:
            self.store.guaranteed_update(PDBS, pdb.key, mutate)
        except NotFoundError:
            pass
