"""Deployment controller — pkg/controller/deployment/deployment_controller.go:63.

Declarative rollout over owned ReplicaSets: each distinct pod template gets
its own RS named `{deployment}-{template-hash}` (the reference's
pod-template-hash scheme); RollingUpdate walks the new RS up and old RSes
down inside the maxSurge/maxUnavailable envelope using the RS controller's
reconciled ready counts; Recreate scales every old RS to zero before
bringing the new one up. Scale (spec.replicas changes against an unchanged
template) adjusts the current RS in place.
"""
from __future__ import annotations

import hashlib
import json

from kubernetes_tpu.api.types import Deployment, ReplicaSet
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, DEPLOYMENTS, REPLICASETS, AlreadyExistsError, NotFoundError,
)


def template_hash(template) -> str:
    """Stable short hash of a pod template (pod-template-hash analog)."""
    from kubernetes_tpu.api import serde
    blob = json.dumps(serde.to_dict(template), sort_keys=True)
    return hashlib.md5(blob.encode()).hexdigest()[:10]


class DeploymentController(DirtyKeyController):
    KIND = DEPLOYMENTS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        self.recorder = EventRecorder(store, component="controllermanager")

    def _register_extra_handlers(self) -> None:
        rs = self.informers.informer(REPLICASETS)
        rs.add_event_handler(on_add=self._rs_changed,
                             on_update=lambda o, n: self._rs_changed(n),
                             on_delete=self._rs_changed)

    def _rs_changed(self, rs: ReplicaSet) -> None:
        if rs.owner_ref is not None and rs.owner_ref[0] == "Deployment":
            self._dirty.add(f"{rs.namespace}/{rs.owner_ref[1]}")

    # -- syncDeployment ------------------------------------------------------
    def _owned_rs(self, dep: Deployment) -> list[ReplicaSet]:
        sets, _rv = self.store.list(REPLICASETS)
        return [r for r in sets
                if r.namespace == dep.namespace and r.owner_ref is not None
                and r.owner_ref[:2] == ("Deployment", dep.name)]

    def _scale_rs(self, rs_key: str, replicas: int) -> None:
        def mutate(cur):
            if cur.replicas == replicas:
                return None
            cur.replicas = replicas
            return cur
        try:
            self.store.guaranteed_update(REPLICASETS, rs_key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass

    def reconcile(self, dep: Deployment) -> None:
        if dep.template is None or dep.paused:
            return
        if dep.strategy == "RollingUpdate" and dep.max_surge <= 0 \
                and dep.max_unavailable <= 0:
            # the reference's apiserver validation rejects this combination
            # (a rollout could neither surge nor shed — permanent livelock);
            # surface it instead of silently converging to a no-op
            self.recorder.event(
                "Deployment", dep.key, "Warning", "InvalidSpec",
                "maxSurge and maxUnavailable may not both be 0")
            return
        rev = template_hash(dep.template)
        new_name = f"{dep.name}-{rev}"
        owned = self._owned_rs(dep)
        new_rs = next((r for r in owned if r.name == new_name), None)
        old = [r for r in owned if r.name != new_name]
        if new_rs is None:
            # getNewReplicaSet: create the revision's RS; its selector adds
            # the template-hash label so revisions don't claim each other's
            # pods (pod-template-hash, deployment/sync.go)
            from kubernetes_tpu.api.types import LabelSelector
            tmpl = _clone_template(dep.template)
            tmpl.labels = dict(tmpl.labels)
            tmpl.labels["pod-template-hash"] = rev
            base = dict(dep.selector.match_labels) if dep.selector else {}
            base["pod-template-hash"] = rev
            new_rs = ReplicaSet(
                name=new_name, namespace=dep.namespace,
                selector=LabelSelector(match_labels=tuple(sorted(base.items()))),
                replicas=0, template=tmpl,
                owner_ref=("Deployment", dep.name, f"deploy-{dep.name}"))
            try:
                self.store.create(REPLICASETS, new_rs)
                self.recorder.event(
                    "Deployment", dep.key, NORMAL, "ScalingReplicaSet",
                    f"Scaled up replica set {new_name} to start rollout")
            except AlreadyExistsError:
                new_rs = self.store.get(REPLICASETS, f"{dep.namespace}/{new_name}")

        all_pods, _rv = self.store.list(PODS)
        old_total = sum(r.replicas for r in old)
        if dep.strategy == "Recreate":
            # scale all old to zero; bring the new one up only when every
            # old pod is gone (deployment/recreate.go)
            for r in old:
                self._scale_rs(r.key, 0)
            if any(self._counts(r, all_pods)[0] for r in old):
                return
            self._scale_rs(new_rs.key, dep.replicas)
            for r in old:   # drained revisions don't accumulate
                try:
                    self.store.delete(REPLICASETS, r.key)
                except NotFoundError:
                    pass
        else:
            # RollingUpdate (deployment/rolling.go): scale new up within the
            # surge envelope, old down within the availability floor.
            # Availability is counted from LIVE pod phases, not the lagging
            # RS status, so a stale status can never delete healthy pods.
            max_total = dep.replicas + max(dep.max_surge, 0)
            new_target = min(dep.replicas, new_rs.replicas
                             + max(0, max_total - (new_rs.replicas + old_total)))
            if new_target != new_rs.replicas:
                self._scale_rs(new_rs.key, new_target)
            ready_total = self._counts(new_rs, all_pods)[1] + sum(
                self._counts(r, all_pods)[1] for r in old)
            min_available = dep.replicas - max(dep.max_unavailable, 0)
            room = max(0, ready_total - min_available)
            for r in sorted(old, key=lambda r: r.name):
                # cleanupUnhealthyReplicas: not-ready old pods don't count
                # toward availability — shed them first, beyond any room
                total_r, ready_r = self._counts(r, all_pods)
                unhealthy = max(0, min(r.replicas, total_r) - ready_r)
                cut = min(r.replicas, unhealthy + room)
                if cut > 0:
                    self._scale_rs(r.key, r.replicas - cut)
                    room -= max(0, cut - unhealthy)
            # fully-drained old sets are deleted (their pods are gone); the
            # GC would cascade anyway but the rollout owns this cleanup
            for r in old:
                if r.replicas == 0 and not self._counts(r, all_pods)[0]:
                    try:
                        self.store.delete(REPLICASETS, r.key)
                    except NotFoundError:
                        pass
        self._update_status(dep, new_rs, all_pods)

    def _counts(self, rs: ReplicaSet, pods: list) -> tuple[int, int]:
        """(live, ready) pod counts for one RS against a pod list the caller
        fetched once per reconcile. Applies the same ClaimPods owner filter
        as ReplicaSetController._matching_pods so foreign pods with
        coincidentally-matching labels never inflate availability."""
        if rs.selector is None:
            return 0, 0
        mine = [p for p in pods
                if p.namespace == rs.namespace and not p.deleted
                and rs.selector.matches(p.labels)
                and (p.owner_ref is None
                     or p.owner_ref[:2] == ("ReplicaSet", rs.name))]
        return len(mine), sum(1 for p in mine if p.phase == "Running")

    def _update_status(self, dep: Deployment, new_rs: ReplicaSet,
                       all_pods: list) -> None:
        updated, updated_ready = self._counts(new_rs, all_pods)
        ready = updated_ready + sum(self._counts(r, all_pods)[1]
                                    for r in self._owned_rs(dep)
                                    if r.name != new_rs.name)
        rev = template_hash(dep.template)

        def mutate(cur):
            if (cur.observed_revision == rev
                    and cur.updated_replicas == updated
                    and cur.ready_replicas == ready
                    and cur.available_replicas == ready):
                return None
            cur.observed_revision = rev
            cur.updated_replicas = updated
            cur.ready_replicas = ready
            cur.available_replicas = ready
            return cur
        try:
            self.store.guaranteed_update(DEPLOYMENTS, dep.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass


def _clone_template(t):
    import copy
    return copy.deepcopy(t)
