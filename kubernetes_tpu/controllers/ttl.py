"""TTL controller — pkg/controller/ttl/ttl_controller.go.

Annotates every Node with `node.alpha.kubernetes.io/ttl`: how long its
kubelet may cache secrets/configmaps, scaled to cluster size so the
apiserver isn't hammered by refreshes in large clusters. The reference's
boundary table with hysteresis (ttl_controller.go ttlBoundaries): the TTL
steps up when the cluster grows past sizeMax and back down only below
sizeMin, so oscillating around a boundary doesn't flap the annotation."""
from __future__ import annotations

from kubernetes_tpu.api.types import Node
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.store import Store, NODES, NotFoundError

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

# (sizeMin, sizeMax, ttlSeconds) — ttl_controller.go:48 ttlBoundaries
BOUNDARIES = [
    (0, 100, 0),
    (90, 500, 15),
    (450, 1000, 30),
    (900, 2000, 60),
    (1800, 10000, 300),
    (9000, 1 << 62, 600),
]


class TTLController(DirtyKeyController):
    KIND = NODES

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        self._boundary = 0   # current index; moves with hysteresis
        self._want: int | None = None   # computed once per pump/sync

    def _desired_ttl(self) -> int:
        size = len(self.informers.informer(NODES).list())
        i = self._boundary
        while i + 1 < len(BOUNDARIES) and size > BOUNDARIES[i][1]:
            i += 1   # grew past sizeMax: step up
        while i > 0 and size < BOUNDARIES[i][0]:
            i -= 1   # shrank below sizeMin: step down
        self._boundary = i
        return BOUNDARIES[i][2]

    def pump(self) -> int:
        self.informers.pump_all()
        want = self._desired_ttl()
        if want != getattr(self, "_last_want", None):
            # the boundary moved: EVERY node's annotation is stale, not
            # just the ones with fresh events
            self._last_want = want
            for n in self.informers.informer(NODES).list():
                self._dirty.add(n.key)
        self._want = want
        return self.reconcile_dirty()

    def sync(self) -> None:
        self.informers.sync_all()
        self._want = self._last_want = self._desired_ttl()
        for n in self.informers.informer(NODES).list():
            self._dirty.add(n.key)
        self.reconcile_dirty()

    def reconcile(self, node: Node) -> None:
        # pump()/sync() computed _want once; recompute only when reconcile
        # is driven some other way (getattr's eager default would re-list
        # all nodes per node — O(N^2) on a boundary step)
        if self._want is None:
            self._want = self._desired_ttl()
        want = str(self._want)
        if node.annotations.get(TTL_ANNOTATION) == want:
            return

        def mutate(cur):
            if cur.annotations.get(TTL_ANNOTATION) == want:
                return None
            cur.annotations = {**cur.annotations, TTL_ANNOTATION: want}
            return cur
        try:
            self.store.guaranteed_update(NODES, node.key, mutate,
                                        allow_skip=True)
        except NotFoundError:
            pass
