"""Controller manager — the kube-controller-manager shell.

Mirror of cmd/kube-controller-manager/app/controllermanager.go:372
(NewControllerInitializers + StartControllers): owns controller instances
over one store, syncs their informers, and drives reconciliation. The
reference runs 31 loops; this hosts the ones implemented so far and is the
registration point for the rest.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from kubernetes_tpu.store.store import Store
from kubernetes_tpu.controllers.disruption import DisruptionController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.podgc import PodGCController
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.statefulset import StatefulSetController
from kubernetes_tpu.controllers.namespace import (
    NamespaceController, ServiceAccountController,
)
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.hpa import HorizontalPodAutoscalerController
from kubernetes_tpu.controllers.cronjob import CronJobController
from kubernetes_tpu.controllers.podgroup import PodGroupController
from kubernetes_tpu.controllers.ttl import TTLController
from kubernetes_tpu.controllers.pvbinder import PersistentVolumeBinder
from kubernetes_tpu.controllers.nodeipam import NodeIpamController
from kubernetes_tpu.controllers.clusterrole_aggregation import (
    ClusterRoleAggregationController,
)

# name -> constructor(store) (NewControllerInitializers analog,
# controllermanager.go:372-412). Ordering matters for single-threaded
# pump() convergence: deployment before replicaset (rollout scales feed the
# RS reconcile in the same pass), garbagecollector last (owners deleted by
# earlier loops cascade in the same pump).
CONTROLLER_INITIALIZERS: dict[str, Callable[[Store], object]] = {
    "disruption": DisruptionController,
    "podgroup": PodGroupController,
    "nodelifecycle": NodeLifecycleController,
    "podgc": PodGCController,
    "ttl": TTLController,
    "nodeipam": NodeIpamController,
    "clusterrole-aggregation": ClusterRoleAggregationController,
    "persistentvolume-binder": PersistentVolumeBinder,
    "horizontalpodautoscaling": HorizontalPodAutoscalerController,
    "cronjob": CronJobController,
    "deployment": DeploymentController,
    "replicaset": ReplicaSetController,
    "job": JobController,
    "daemonset": DaemonSetController,
    "statefulset": StatefulSetController,
    "endpoint": EndpointsController,
    "resourcequota": ResourceQuotaController,
    "namespace": NamespaceController,
    "serviceaccount": ServiceAccountController,
    "garbagecollector": GarbageCollector,
}


class ControllerManager:
    def __init__(self, store: Store,
                 enabled: Optional[list[str]] = None):
        names = list(CONTROLLER_INITIALIZERS) if enabled is None else enabled
        self.controllers = {
            name: CONTROLLER_INITIALIZERS[name](store) for name in names}
        self._stop = threading.Event()

    def sync(self) -> None:
        for c in self.controllers.values():
            c.sync()

    def pump(self) -> int:
        return sum(c.pump() for c in self.controllers.values())

    def run(self, interval: float = 0.05,
            stop_after: Optional[Callable[[], bool]] = None) -> None:
        """Reconcile loop; call from a thread."""
        while not self._stop.is_set():
            self.pump()
            if stop_after is not None and stop_after():
                return
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
