"""PodGroup controller — gang phase/timeout reconciliation.

The coscheduling analog of the sig-scheduling PodGroup controller
(pkg/controller in scheduler-plugins): watches PodGroups and their member
pods (the `pod-group.kubernetes-tpu/name` label) and reconciles status:

- members/scheduled counts from live pods;
- phase: Pending -> PreScheduling once minMember members exist,
  -> Scheduled once >= minMember members are BOUND,
  -> Unschedulable once schedule_timeout_seconds elapses without reaching
  Scheduled (a later successful placement flips it back — eviction or
  member deletion can likewise drop a Scheduled group back to
  PreScheduling, matching the live counts);
- a Warning event on the timeout transition (the user-visible audit of a
  gang that never formed).

The scheduler shell owns the PreScheduling write on its first attempt so
the phase flips even between controller pumps; this controller is the
authority that converges status with reality afterwards.
"""
from __future__ import annotations

from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.coscheduling.types import (
    PHASE_PENDING, PHASE_PRESCHEDULING, PHASE_SCHEDULED, PHASE_UNSCHEDULABLE,
    PodGroup, pod_group_name,
)
from kubernetes_tpu.store.record import EventRecorder, WARNING
from kubernetes_tpu.store.store import Store, PODGROUPS, PODS, NotFoundError
from kubernetes_tpu.utils.clock import RealClock


class PodGroupController(DirtyKeyController):
    KIND = PODGROUPS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        self.clock = clock or RealClock()
        self.recorder = EventRecorder(store, component="podgroup-controller")
        # timeout base for groups created without a creation_timestamp:
        # first time THIS controller observed the group
        self._first_seen: dict[str, float] = {}

    def _register_extra_handlers(self) -> None:
        pods = self.informers.informer(PODS)

        def dirty_owner(pod) -> None:
            name = pod_group_name(pod)
            if name:
                self._mark_dirty(f"{pod.namespace}/{name}")

        pods.add_event_handler(
            on_add=dirty_owner,
            on_update=lambda _old, new: dirty_owner(new),
            on_delete=dirty_owner)

    def reconcile(self, group: PodGroup) -> None:
        now = self.clock.now()
        base = group.creation_timestamp \
            or self._first_seen.setdefault(group.key, now)
        members = [
            p for p in self.informers.informer(PODS).list()
            if p.namespace == group.namespace
            and pod_group_name(p) == group.name]
        n_members = len(members)
        n_bound = sum(1 for p in members if p.node_name)
        min_member = max(group.min_member, 1)
        timed_out = (group.schedule_timeout_seconds is not None
                     and now - base > group.schedule_timeout_seconds)
        if n_bound >= min_member:
            want = PHASE_SCHEDULED
        elif timed_out:
            want = PHASE_UNSCHEDULABLE
        elif group.phase == PHASE_UNSCHEDULABLE:
            want = PHASE_UNSCHEDULABLE   # terminal until placement succeeds
        elif n_members >= min_member or n_bound > 0:
            # enough members exist (or some are already bound — a formerly
            # Scheduled group that lost members below minMember); the
            # scheduler is (or will be) trying — don't regress a
            # PreScheduling the shell already wrote
            want = PHASE_PRESCHEDULING
        elif group.phase == PHASE_PRESCHEDULING and n_members > 0:
            want = PHASE_PRESCHEDULING
        else:
            want = PHASE_PENDING
        if want == group.phase and n_members == group.members \
                and n_bound == group.scheduled:
            return
        try:
            self.store.update_pod_group_status(
                group.key, phase=want, members=n_members,
                scheduled=n_bound, now=now)
        except NotFoundError:
            return
        if want == PHASE_UNSCHEDULABLE and group.phase != PHASE_UNSCHEDULABLE:
            # the gang never formed inside its window — the audit record
            self.recorder.event(
                "PodGroup", group.key, WARNING, "GangTimeout",
                f"pod group {group.key} did not reach minMember="
                f"{min_member} within {group.schedule_timeout_seconds}s "
                f"({n_bound} bound of {n_members} members)")
