"""ReplicaSet controller — pkg/controller/replicaset/replica_set.go.

The workload-management loop: for every ReplicaSet (which also stands in
for RC/StatefulSet in this pruned model), reconcile the number of matching
live pods to spec.replicas — creating owned pods from the set's template
shape when short (syncReplicaSet -> manageReplicas), deleting the
youngest surplus pods when over (the reference prefers not-ready/younger
pods via ActivePods ordering; creation time is the pruned criterion here).
Owned pods carry owner_ref so the disruption controller's expected-scale
walk and PodGC recognize them.
"""
from __future__ import annotations

import itertools
from typing import Optional

from kubernetes_tpu.api.types import Pod, Container, ReplicaSet
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, REPLICASETS, AlreadyExistsError, NotFoundError,
)

_suffix = itertools.count(1)


class ReplicaSetController(DirtyKeyController):
    KIND = REPLICASETS

    def __init__(self, store: Store, clock=None, admission=None):
        super().__init__(store, clock=clock)
        # controller-originated pod writes go through the same admission
        # chain as kubectl-path writes (LimitRanger defaults, PriorityClass
        # resolution, toleration defaulting, quota), so scale-up pods are
        # not shaped differently from user-created ones — in the reference
        # every controller write passes apiserver admission
        from kubernetes_tpu.apiserver.admission import AdmissionChain
        self.admission = admission if admission is not None else AdmissionChain()
        self.recorder = EventRecorder(store, component="controllermanager")

    def _register_extra_handlers(self) -> None:
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=self._pod_changed,
                               on_update=lambda o, n: self._pod_changed(n),
                               on_delete=self._pod_changed)

    def _pod_changed(self, pod: Pod) -> None:
        if pod.owner_ref is not None:
            kind, name, _uid = pod.owner_ref
            self._dirty.add(f"{pod.namespace}/{name}")
        else:
            # orphan adoption path: any selector might match it
            for r in self.informers.informer(REPLICASETS).list():
                self._dirty.add(r.key)

    def reconcile(self, rs: ReplicaSet) -> None:
        self.manage_replicas(rs)

    # -- syncReplicaSet -> manageReplicas ------------------------------------
    def _matching_pods(self, rs: ReplicaSet) -> list[Pod]:
        if rs.selector is None:
            return []
        pods, _rv = self.store.list(PODS)
        return [p for p in pods
                if p.namespace == rs.namespace and not p.deleted
                and rs.selector.matches(p.labels)
                # adopt orphans; never count pods owned by a DIFFERENT
                # controller (a Job pod with overlapping labels is not ours
                # — reference ControllerRefManager ClaimPods)
                and (p.owner_ref is None
                     or p.owner_ref[:2] == ("ReplicaSet", rs.name))]

    def _template_pod(self, rs: ReplicaSet) -> Pod:
        owner = ("ReplicaSet", rs.name, f"rs-{rs.name}")
        name = f"{rs.name}-{next(_suffix):x}"
        if rs.template is not None:
            extra = dict(rs.selector.match_labels) if rs.selector else {}
            return rs.template.make_pod(name, rs.namespace, owner_ref=owner,
                                        extra_labels=extra)
        labels = dict(rs.selector.match_labels) if rs.selector else {}
        return Pod(name=name,
                   namespace=rs.namespace, labels=labels,
                   owner_ref=owner,
                   containers=(Container.make(name="c"),))

    def manage_replicas(self, rs: ReplicaSet) -> None:
        pods = self._matching_pods(rs)
        diff = rs.replicas - len(pods)
        if diff > 0:
            from kubernetes_tpu.apiserver.admission import AdmissionError
            for _ in range(diff):
                pod = self._template_pod(rs)
                admitted = None
                try:
                    pod = admitted = self.admission.admit(PODS, pod, self.store)
                    self.store.create(PODS, pod)
                except AlreadyExistsError:
                    # the admitted create never landed: refund quota charges
                    self.admission.refund(PODS, admitted, self.store)
                    continue
                except AdmissionError as e:
                    # quota exhausted (etc.): surface and stop this pass —
                    # the remaining creates would fail the same way
                    self.recorder.event(
                        "ReplicaSet", rs.key, "Warning", "FailedCreate",
                        f"Error creating: {e}")
                    break
                self.recorder.event(
                    "ReplicaSet", rs.key, NORMAL, "SuccessfulCreate",
                    f"Created pod: {pod.name}")
        elif diff < 0:
            # scale down: keep-worthiest first (scheduled, then older — the
            # reference's ActivePods ranking deletes unscheduled/younger
            # pods first), then delete the tail beyond spec.replicas
            pods.sort(key=lambda p: (0 if p.node_name else 1,
                                     p.creation_timestamp))
            victims = pods[rs.replicas:]
            for p in victims:
                try:
                    self.store.delete(PODS, p.key)
                except NotFoundError:
                    continue
                self.recorder.event(
                    "ReplicaSet", rs.key, NORMAL, "SuccessfulDelete",
                    f"Deleted pod: {p.name}")
        self._update_status(rs)

    def _update_status(self, rs: ReplicaSet) -> None:
        """calculateStatus analog: observed + ready replica counts the
        deployment controller's rollout gating reads."""
        pods = self._matching_pods(rs)
        observed = len(pods)
        ready = sum(1 for p in pods if p.phase == "Running")
        if observed == rs.observed_replicas and ready == rs.ready_replicas:
            return

        def mutate(cur):
            if cur.observed_replicas == observed \
                    and cur.ready_replicas == ready:
                return None
            cur.observed_replicas = observed
            cur.ready_replicas = ready
            return cur
        try:
            self.store.guaranteed_update(REPLICASETS, rs.key, mutate,
                                         allow_skip=True)
        except NotFoundError:
            pass
