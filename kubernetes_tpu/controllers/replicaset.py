"""ReplicaSet controller — pkg/controller/replicaset/replica_set.go.

The workload-management loop: for every ReplicaSet (which also stands in
for RC/StatefulSet in this pruned model), reconcile the number of matching
live pods to spec.replicas — creating owned pods from the set's template
shape when short (syncReplicaSet -> manageReplicas), deleting the
youngest surplus pods when over (the reference prefers not-ready/younger
pods via ActivePods ordering; creation time is the pruned criterion here).
Owned pods carry owner_ref so the disruption controller's expected-scale
walk and PodGC recognize them.
"""
from __future__ import annotations

import itertools
from typing import Optional

from kubernetes_tpu.api.types import Pod, Container, ReplicaSet
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PODS, REPLICASETS, AlreadyExistsError, NotFoundError,
)

_suffix = itertools.count(1)


class ReplicaSetController:
    def __init__(self, store: Store, clock=None, admission=None):
        self.store = store
        # controller-originated pod writes go through the same admission
        # chain as kubectl-path writes (LimitRanger defaults, PriorityClass
        # resolution, toleration defaulting, quota), so scale-up pods are
        # not shaped differently from user-created ones — in the reference
        # every controller write passes apiserver admission
        from kubernetes_tpu.apiserver.admission import AdmissionChain
        self.admission = admission if admission is not None else AdmissionChain()
        self.recorder = EventRecorder(store, component="controllermanager")
        self.informers = InformerFactory(store)
        self._dirty: set[str] = set()
        rs = self.informers.informer(REPLICASETS)
        rs.add_event_handler(on_add=lambda r: self._dirty.add(r.key),
                             on_update=lambda o, n: self._dirty.add(n.key),
                             on_delete=lambda r: self._dirty.discard(r.key))
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=self._pod_changed,
                               on_update=lambda o, n: self._pod_changed(n),
                               on_delete=self._pod_changed)

    def _pod_changed(self, pod: Pod) -> None:
        if pod.owner_ref is not None:
            kind, name, _uid = pod.owner_ref
            self._dirty.add(f"{pod.namespace}/{name}")
        else:
            # orphan adoption path: any selector might match it
            for r in self.informers.informer(REPLICASETS).list():
                self._dirty.add(r.key)

    def sync(self) -> None:
        self.informers.sync_all()
        for r in self.informers.informer(REPLICASETS).list():
            self._dirty.add(r.key)
        self.reconcile_dirty()

    def pump(self) -> int:
        self.informers.pump_all()
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty:
            key = self._dirty.pop()
            try:
                rs = self.store.get(REPLICASETS, key)
            except NotFoundError:
                continue
            self.manage_replicas(rs)
            n += 1
        return n

    # -- syncReplicaSet -> manageReplicas ------------------------------------
    def _matching_pods(self, rs: ReplicaSet) -> list[Pod]:
        if rs.selector is None:
            return []
        pods, _rv = self.store.list(PODS)
        return [p for p in pods
                if p.namespace == rs.namespace and not p.deleted
                and rs.selector.matches(p.labels)]

    def _template_pod(self, rs: ReplicaSet) -> Pod:
        labels = dict(rs.selector.match_labels) if rs.selector else {}
        return Pod(name=f"{rs.name}-{next(_suffix):x}",
                   namespace=rs.namespace, labels=labels,
                   owner_ref=("ReplicaSet", rs.name, f"rs-{rs.name}"),
                   containers=(Container.make(name="c"),))

    def manage_replicas(self, rs: ReplicaSet) -> None:
        pods = self._matching_pods(rs)
        diff = rs.replicas - len(pods)
        if diff > 0:
            from kubernetes_tpu.apiserver.admission import AdmissionError
            for _ in range(diff):
                pod = self._template_pod(rs)
                admitted = None
                try:
                    pod = admitted = self.admission.admit(PODS, pod, self.store)
                    self.store.create(PODS, pod)
                except AlreadyExistsError:
                    # the admitted create never landed: refund quota charges
                    self.admission.refund(PODS, admitted, self.store)
                    continue
                except AdmissionError as e:
                    # quota exhausted (etc.): surface and stop this pass —
                    # the remaining creates would fail the same way
                    self.recorder.event(
                        "ReplicaSet", rs.key, "Warning", "FailedCreate",
                        f"Error creating: {e}")
                    break
                self.recorder.event(
                    "ReplicaSet", rs.key, NORMAL, "SuccessfulCreate",
                    f"Created pod: {pod.name}")
        elif diff < 0:
            # scale down: keep-worthiest first (scheduled, then older — the
            # reference's ActivePods ranking deletes unscheduled/younger
            # pods first), then delete the tail beyond spec.replicas
            pods.sort(key=lambda p: (0 if p.node_name else 1,
                                     p.creation_timestamp))
            victims = pods[rs.replicas:]
            for p in victims:
                try:
                    self.store.delete(PODS, p.key)
                except NotFoundError:
                    continue
                self.recorder.event(
                    "ReplicaSet", rs.key, NORMAL, "SuccessfulDelete",
                    f"Deleted pod: {p.name}")
