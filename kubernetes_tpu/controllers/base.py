"""Shared controller scaffold: informer-driven dirty-key reconciliation.

Every workload controller follows the reference's controller shape
(informer event handlers -> workqueue -> syncHandler; e.g.
pkg/controller/deployment/deployment_controller.go:63): the primary kind's
events mark keys dirty, reconcile_dirty drains them through reconcile().
Subclasses set KIND, implement reconcile(obj), and add any secondary-kind
handlers in _register_extra_handlers().
"""
from __future__ import annotations

from typing import Any

from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.store import Store, NotFoundError


class DirtyKeyController:
    KIND: str = ""

    def __init__(self, store: Store, clock=None):
        self.store = store
        self.clock = clock
        self.informers = InformerFactory(store)
        self._dirty: set[str] = set()
        prim = self.informers.informer(self.KIND)
        prim.add_event_handler(
            on_add=lambda o: self._dirty.add(o.key),
            on_update=lambda o, n: self._dirty.add(n.key),
            on_delete=lambda o: self._dirty.discard(o.key))
        self._register_extra_handlers()

    def _register_extra_handlers(self) -> None:
        """Secondary-kind informer wiring (pods -> owner dirty, etc.)."""

    def sync(self) -> None:
        self.informers.sync_all()
        for o in self.informers.informer(self.KIND).list():
            self._dirty.add(o.key)
        self.reconcile_dirty()

    def pump(self) -> int:
        self.informers.pump_all()
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty:
            key = self._dirty.pop()
            try:
                obj = self.store.get(self.KIND, key)
            except NotFoundError:
                continue
            self.reconcile(obj)
            n += 1
        return n

    def reconcile(self, obj: Any) -> None:
        raise NotImplementedError
