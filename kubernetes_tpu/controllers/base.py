"""Shared controller scaffold: informer-driven dirty-key reconciliation.

Every workload controller follows the reference's controller shape
(informer event handlers -> workqueue -> syncHandler; e.g.
pkg/controller/deployment/deployment_controller.go:63): the primary kind's
events mark keys dirty, reconcile_dirty drains them through reconcile().
Subclasses set KIND, implement reconcile(obj), and add any secondary-kind
handlers in _register_extra_handlers().

Workqueue metrics (the k8s.io/client-go/util/workqueue metrics-provider
analog, labeled by controller class name): depth, adds, queue-wait and
work durations, retries. A reconcile() exception re-queues the key (so
the work isn't lost) and counts as a retry before propagating.
"""
from __future__ import annotations

import time
from typing import Any

from kubernetes_tpu import obs
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.store import Store, NotFoundError

WQ_DEPTH = obs.gauge(
    "workqueue_depth", "Current dirty-key queue depth, by controller.",
    ("name",))
WQ_ADDS = obs.counter(
    "workqueue_adds_total", "Keys marked dirty, by controller.", ("name",))
WQ_QUEUE_DURATION = obs.histogram(
    "workqueue_queue_duration_seconds",
    "Time keys wait dirty before reconcile, by controller.", ("name",))
WQ_WORK_DURATION = obs.histogram(
    "workqueue_work_duration_seconds",
    "Time reconcile() spends per key, by controller.", ("name",))
WQ_RETRIES = obs.counter(
    "workqueue_retries_total",
    "Keys re-queued after a reconcile() exception, by controller.",
    ("name",))


class DirtyKeyController:
    KIND: str = ""

    def __init__(self, store: Store, clock=None):
        self.store = store
        self.clock = clock
        self.informers = InformerFactory(store)
        self._dirty: set[str] = set()
        # wall-clock dirty-mark times for queue_duration (real time, not
        # the injectable scheduling clock: metrics measure this process)
        self._dirty_since: dict[str, float] = {}
        self._wq_name = type(self).__name__
        prim = self.informers.informer(self.KIND)
        prim.add_event_handler(
            on_add=lambda o: self._mark_dirty(o.key),
            on_update=lambda o, n: self._mark_dirty(n.key),
            on_delete=lambda o: self._unmark_dirty(o.key))
        self._register_extra_handlers()

    def _register_extra_handlers(self) -> None:
        """Secondary-kind informer wiring (pods -> owner dirty, etc.)."""

    # -- workqueue ----------------------------------------------------------
    def _mark_dirty(self, key: str) -> None:
        if key not in self._dirty:
            self._dirty.add(key)
            self._dirty_since[key] = time.perf_counter()
            WQ_ADDS.labels(self._wq_name).inc()
            WQ_DEPTH.labels(self._wq_name).set(len(self._dirty))

    def _unmark_dirty(self, key: str) -> None:
        self._dirty.discard(key)
        self._dirty_since.pop(key, None)
        WQ_DEPTH.labels(self._wq_name).set(len(self._dirty))

    def sync(self) -> None:
        self.informers.sync_all()
        for o in self.informers.informer(self.KIND).list():
            self._mark_dirty(o.key)
        self.reconcile_dirty()

    def pump(self) -> int:
        self.informers.pump_all()
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        name = self._wq_name
        while self._dirty:
            key = self._dirty.pop()
            marked = self._dirty_since.pop(key, None)
            now = time.perf_counter()
            if marked is not None:
                WQ_QUEUE_DURATION.labels(name).observe(now - marked)
            WQ_DEPTH.labels(name).set(len(self._dirty))
            try:
                obj = self.store.get(self.KIND, key)
            except NotFoundError:
                continue
            try:
                self.reconcile(obj)
            except Exception:
                # the reference workqueue re-queues on syncHandler error
                # (AddRateLimited); keep the key so the work isn't lost,
                # count the retry, and let the error propagate
                WQ_RETRIES.labels(name).inc()
                self._mark_dirty(key)
                raise
            finally:
                WQ_WORK_DURATION.labels(name).observe(
                    time.perf_counter() - now)
            n += 1
        return n

    def reconcile(self, obj: Any) -> None:
        raise NotImplementedError
