"""PersistentVolume binder — pkg/controller/volume/persistentvolume/
pv_controller.go.

The Immediate-binding half of the reference's claim/volume sync: an
unbound PVC gets the smallest unclaimed PV matching its storage class and
capacity (syncUnboundClaim -> findBestMatchForClaim), written as the
claim_ref/volume_name pair from both sides. The scheduler's
CheckVolumeBinding predicate keeps handling whatever is still unbound at
scheduling time (the WaitForFirstConsumer-shaped path), so PVCs now bind
OUTSIDE scheduling cycles too — the gap VERDICT r4 named.

Reclaim follows the reference default (Retain): deleting a PVC leaves its
PV's claim_ref pointing at the vanished claim — Released, never
rebound."""
from __future__ import annotations

from kubernetes_tpu.api.types import PersistentVolumeClaim
from kubernetes_tpu.controllers.base import DirtyKeyController
from kubernetes_tpu.store.record import EventRecorder, NORMAL
from kubernetes_tpu.store.store import (
    Store, PVS, PVCS, ConflictError, NotFoundError,
)


class PersistentVolumeBinder(DirtyKeyController):
    KIND = PVCS

    def __init__(self, store: Store, clock=None):
        super().__init__(store, clock=clock)
        self.recorder = EventRecorder(store, component="persistentvolume-binder")

    def _register_extra_handlers(self) -> None:
        # a PV appearing/releasing can unblock pending claims
        pvs = self.informers.informer(PVS)
        mark = lambda *_: self._dirty.update(
            c.key for c in self.informers.informer(PVCS).list()
            if not c.volume_name)
        pvs.add_event_handler(on_add=mark, on_update=mark, on_delete=mark)

    def _find_best_match(self, pvc: PersistentVolumeClaim):
        """findBestMatchForClaim: smallest unclaimed PV that satisfies the
        class + capacity request (the scheduler's VolumeBinder uses the
        same rule per node; here binding is node-agnostic Immediate
        mode)."""
        best = None
        for pv in self.store.list(PVS)[0]:
            if pv.claim_ref:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if pv.capacity < pvc.request:
                continue
            if best is None or pv.capacity < best.capacity:
                best = pv
        return best

    def reconcile(self, pvc: PersistentVolumeClaim) -> None:
        if pvc.volume_name:
            return   # bound (by us or by the scheduler's bind path)
        pv = self._find_best_match(pvc)
        if pv is None:
            return   # stays Pending; a future PV event re-dirties it
        # claim the PV first with a CAS so two binders (or the scheduler's
        # volume binder) can't hand one PV to two claims; losing the race
        # just retries with the next event
        def claim(cur, _key=pvc.key):
            if cur.claim_ref:
                return None
            cur.claim_ref = _key
            return cur
        try:
            updated = self.store.guaranteed_update(PVS, pv.name, claim,
                                                   allow_skip=True)
        except NotFoundError:
            return
        if updated.claim_ref != pvc.key:
            self._dirty.add(pvc.key)   # lost the race: try another PV
            return

        def bind(cur, _pv=pv.name):
            if cur.volume_name:
                return None   # raced: the scheduler's binder got there
            cur.volume_name = _pv
            return cur

        def release(cur):
            if cur.claim_ref != pvc.key:
                return None
            cur.claim_ref = ""
            return cur
        try:
            bound = self.store.guaranteed_update(PVCS, pvc.key, bind,
                                                 allow_skip=True)
        except NotFoundError:
            bound = None   # claim vanished between match and write
        if bound is None or bound.volume_name != pv.name:
            # we didn't win the claim side: give the CAS'd PV back or it
            # leaks as claimed-by-nobody forever (Retain never releases)
            try:
                self.store.guaranteed_update(PVS, pv.name, release,
                                             allow_skip=True)
            except NotFoundError:
                pass
            return
        self.recorder.event("PersistentVolumeClaim", pvc.key, NORMAL,
                            "Bound", f"bound to volume {pv.name}")
