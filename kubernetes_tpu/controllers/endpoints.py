"""Endpoints controller — pkg/controller/endpoint/endpoints_controller.go.

The first loop in the reference's controller list: for every Service,
maintain an Endpoints object naming the ready pods its selector matches.
Address identity is (pod_key, node_name) — the pruned model has no pod IPs,
and the node is what a proxy would route to. Only bound, ready pods count
(the reference filters through IsPodReady the same way).
"""
from __future__ import annotations

from kubernetes_tpu.api.types import Endpoints, Pod, Service
from kubernetes_tpu.store.informer import InformerFactory
from kubernetes_tpu.store.store import (
    Store, PODS, SERVICES, ENDPOINTS, AlreadyExistsError, NotFoundError,
)


def _is_ready(pod: Pod) -> bool:
    if not pod.node_name or pod.deleted:
        return False
    for c in pod.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return True   # no kubelet reported readiness: bound counts as ready


class EndpointsController:
    def __init__(self, store: Store):
        self.store = store
        self.informers = InformerFactory(store)
        self._dirty: set[str] = set()
        svcs = self.informers.informer(SERVICES)
        svcs.add_event_handler(
            on_add=lambda s: self._dirty.add(s.key),
            on_update=lambda o, n: self._dirty.add(n.key),
            on_delete=self._service_deleted)
        pods = self.informers.informer(PODS)
        pods.add_event_handler(on_add=lambda p: self._mark_all(),
                               on_update=lambda o, n: self._mark_all(),
                               on_delete=lambda p: self._mark_all())

    def _service_deleted(self, svc: Service) -> None:
        self._dirty.discard(svc.key)
        try:
            self.store.delete(ENDPOINTS, svc.key)
        except NotFoundError:
            pass

    def _mark_all(self) -> None:
        for s in self.informers.informer(SERVICES).list():
            self._dirty.add(s.key)

    def sync(self) -> None:
        self.informers.sync_all()
        self._mark_all()
        self.reconcile_dirty()

    def pump(self) -> int:
        self.informers.pump_all()
        return self.reconcile_dirty()

    def reconcile_dirty(self) -> int:
        n = 0
        while self._dirty:
            key = self._dirty.pop()
            try:
                svc = self.store.get(SERVICES, key)
            except NotFoundError:
                continue
            self.reconcile(svc)
            n += 1
        return n

    def reconcile(self, svc: Service) -> None:
        if not svc.selector:
            return   # selectorless services manage their own endpoints
        pods, _rv = self.store.list(PODS)
        addresses = tuple(sorted(
            (p.key, p.node_name) for p in pods
            if p.namespace == svc.namespace and _is_ready(p)
            and all(p.labels.get(k) == v for k, v in svc.selector.items())))
        try:
            current = self.store.get(ENDPOINTS, svc.key)
        except NotFoundError:
            try:
                self.store.create(ENDPOINTS, Endpoints(
                    name=svc.name, namespace=svc.namespace,
                    addresses=addresses))
            except AlreadyExistsError:
                pass
            return
        if current.addresses == addresses:
            return

        def mutate(cur):
            cur.addresses = addresses
            return cur
        try:
            self.store.guaranteed_update(ENDPOINTS, svc.key, mutate)
        except NotFoundError:
            pass
